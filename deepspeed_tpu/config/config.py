"""The single-JSON config tree.

TPU-native re-design of ``deepspeed/runtime/config.py:707``
(``DeepSpeedConfig``) and its per-feature pydantic subtrees.  Field names are
kept JSON-compatible with the reference (``train_batch_size``,
``zero_optimization.stage``, ``bf16.enabled``, ...) so existing DeepSpeed
configs parse unchanged; GPU-only knobs are accepted and ignored with a
warning.  The batch triple reconciliation
(``train_batch_size = micro_batch * gradient_accumulation_steps * dp_world``)
mirrors ``_configure_train_batch_size`` in the reference.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Union

from pydantic import Field, model_validator

from deepspeed_tpu.config.config_utils import ConfigModel
from deepspeed_tpu.utils.logging import logger

# ---------------------------------------------------------------------------
# Precision
# ---------------------------------------------------------------------------


class FP16Config(ConfigModel):
    """``fp16`` subtree (reference ``runtime/fp16/loss_scaler.py`` knobs)."""

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0


class BF16Config(ConfigModel):
    """``bf16`` subtree. On TPU this is the default precision."""

    enabled: bool = False
    # Keep an fp32 master copy of params in the optimizer (reference
    # BF16_Optimizer semantics). Disable for pure-bf16 experiments.
    master_weights: bool = True


# ---------------------------------------------------------------------------
# ZeRO
# ---------------------------------------------------------------------------


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"  # == TPU host memory (pinned_host)
    nvme = "nvme"


class OffloadParamConfig(ConfigModel):
    device: str = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


class OffloadOptimizerConfig(ConfigModel):
    """``offload_optimizer`` subtree.  For ``device=nvme`` the pipeline
    knobs shape the swapped moment stream (reference
    ``pipelined_optimizer_swapper``): ``buffer_count`` page-aligned host
    bucket buffers with up to ``buffer_count - 1`` reads in flight ahead
    of the compute; ``pipeline_read``/``pipeline_write`` toggle the
    read-ahead and the deferred write-back stages (both off = the
    strictly serial stream, bit-identical state — the parity-test
    reference).  Defaults ON (documented divergence from the reference's
    opt-in: the serial stream is latency-bound, measured 0.039 GB/s vs
    1.9 GB/s bulk on the same engine)."""
    device: str = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 3
    pin_memory: bool = False
    pipeline_read: bool = True
    pipeline_write: bool = True
    fast_init: bool = False
    ratio: float = 1.0


class ZeroConfig(ConfigModel):
    """``zero_optimization`` subtree (reference ``runtime/zero/config.py``).

    On TPU the stages map to sharding layouts on the train state rather than
    hook-driven partitioning:

    - stage 0: replicated params/grads/opt state (plain DP; grads ``psum``).
    - stage 1: optimizer state sharded over the data axis.
    - stage 2: stage 1 + gradients reduce-scattered (``psum_scatter``).
    - stage 3: params also sharded; XLA/GSPMD inserts per-layer all-gathers
      (FSDP). ``stage3_max_live_parameters``-style control is expressed with
      scan-over-layers + remat policies instead of a prefetch tracer.
    """

    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    round_robin_gradients: bool = False
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    # ZeRO++ knobs
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    # MiCS
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False

    @model_validator(mode="after")
    def _validate_stage(self):
        if self.stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        return self


# ---------------------------------------------------------------------------
# Optimizer / scheduler
# ---------------------------------------------------------------------------


class OptimizerConfig(ConfigModel):
    type: str = "AdamW"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(ConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Parallel topology
# ---------------------------------------------------------------------------


class TensorParallelConfig(ConfigModel):
    """``tensor_parallel`` subtree (reference ``runtime/tensor_parallel/config.py``)."""

    autotp_size: int = 1
    tp_size: int = 1
    tp_grain_size: int = 1

    @model_validator(mode="after")
    def _merge(self):
        if self.autotp_size > 1 and self.tp_size == 1:
            self.tp_size = self.autotp_size
        return self


class PipelineParallelConfig(ConfigModel):
    stages: int = 1
    partition_method: str = "parameters"
    num_microbatches: Optional[int] = None
    activation_checkpoint_interval: int = 0


class SequenceParallelConfig(ConfigModel):
    size: int = 1
    attention_impl: str = "ulysses"  # ulysses | ring


class ExpertParallelConfig(ConfigModel):
    size: int = 1


# ---------------------------------------------------------------------------
# Aux subsystems
# ---------------------------------------------------------------------------


class ActivationCheckpointingConfig(ConfigModel):
    """Maps to ``jax.checkpoint`` policies rather than torch re-forward."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-specific: which jax.checkpoint policy to use inside scanned layers.
    policy: str = "nothing_saveable"  # nothing_saveable | dots_saveable | everything_saveable


class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class CommsLoggerConfig(ConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class CometConfig(ConfigModel):
    """``comet`` subtree (reference ``deepspeed/monitor/config.py``
    CometConfig / ``monitor/comet.py:23``): metrics stream to a Comet
    experiment, throttled to every ``samples_log_interval`` samples."""
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    mode: Optional[str] = None
    online: Optional[bool] = None


class MonitorConfig(ConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    comet: CometConfig = Field(default_factory=CometConfig)


class CheckpointConfig(ConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    async_save: bool = False


class AioConfig(ConfigModel):
    """``aio`` subtree (reference ``deepspeed/runtime/swap_tensor/
    aio_config.py``): tuning knobs for the native async-IO engine.
    ``python -m deepspeed_tpu.io.bench --sweep`` grids queue_depth x
    block_size x thread_count for read AND write and reports the
    best-write config to paste here (``--tune`` optimizes the combined
    direction).  queue_depth is the per-worker io_uring ring depth (the
    reference's libaio queue_depth; default 128 from the write-parity
    sweep — depth is what hides write submission latency); use_odirect
    bypasses the page cache whenever pointer+offset alignment allows
    (unaligned lengths split into a direct main + buffered tail; enable
    it per mount after a --sweep, the engine falls back cleanly where
    the fs refuses).  single_submit/overlap_events are libaio-era knobs
    accepted for config compatibility."""
    block_size: int = 1 << 20
    queue_depth: int = 128
    thread_count: int = 8
    use_odirect: bool = False
    single_submit: bool = False
    overlap_events: bool = True


class DataTypesConfig(ConfigModel):
    grad_accum_dtype: Optional[str] = None


class CompressionConfig(ConfigModel):
    weight_quantization: Dict[str, Any] = Field(default_factory=dict)
    activation_quantization: Dict[str, Any] = Field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = Field(default_factory=dict)
    row_pruning: Dict[str, Any] = Field(default_factory=dict)
    head_pruning: Dict[str, Any] = Field(default_factory=dict)
    channel_pruning: Dict[str, Any] = Field(default_factory=dict)
    layer_reduction: Dict[str, Any] = Field(default_factory=dict)


class SdcConfig(ConfigModel):
    """``resilience.sdc`` subtree (runtime/swap_tensor.py +
    resilience/sdc.py): silent-data-corruption defense for the NVMe
    offload hot path.  Every bucket/shard the moment stream writes is
    digested (on a side thread, overlapped with the in-flight IO) and
    re-verified on swap-in before the bytes reach the optimizer update;
    a mismatch re-reads with backoff, then quarantines the swap file
    and raises ``SwapCorruptionError`` through the engine's
    emergency-checkpoint path."""

    # verify every swap-in against the write-side digest (off = the
    # pre-defense behavior, byte-identical stream, no digests computed)
    verify_on_read: bool = True
    # digest algorithm: sum64 (numpy-vectorized wraparound word sum,
    # ~4 GB/s/core — default; detects any single flipped bit) |
    # adler32 | crc32 (zlib; slower, stronger burst detection)
    checksum: str = "sum64"
    # blocking re-reads before a mismatching bucket/shard is declared
    # persistently corrupt and quarantined (transient host-buffer/DMA
    # corruption heals here)
    max_reread_retries: int = 2

    @model_validator(mode="after")
    def _validate(self):
        allowed = ("sum64", "adler32", "crc32")
        if self.checksum not in allowed:
            raise ValueError(
                f"resilience.sdc.checksum must be one of {allowed}, "
                f"got {self.checksum!r}")
        if self.max_reread_retries < 0:
            raise ValueError(
                "resilience.sdc.max_reread_retries must be >= 0")
        return self


class CommResilienceConfig(ConfigModel):
    """``resilience.comm`` subtree (deepspeed_tpu/resilience/distributed.py
    + comm/watchdog.py): distributed-health knobs — all off by default,
    and the instrumented comm paths are exact no-ops when off."""

    # eager collectives fail fast with CollectiveTimeout after this many
    # seconds instead of hanging on a dropped/wedged peer (0 = no
    # watchdog).  The engine routes the timeout through the preemption
    # path: emergency checkpoint attempt, then a clean nonzero abort.
    collective_timeout_s: float = 0.0
    # every N steps, cross-check replica-identical scalars (loss, grad
    # norm) across processes; divergence raises GradientAnomalyError
    # (0 = off; enabling costs one small allgather per check)
    desync_interval: int = 0
    # absolute tolerance for the desync comparison (fetched replicas of
    # the same global scalar should be bit-identical; leave 0 unless a
    # transport legitimately perturbs them)
    desync_tolerance: float = 0.0
    # at steps_per_print, aggregate cross-rank collective timings and
    # write the straggler report to the monitor (costs one small
    # allgather per report)
    straggler_report: bool = False

    @model_validator(mode="after")
    def _validate(self):
        if self.collective_timeout_s < 0:
            raise ValueError(
                "resilience.comm.collective_timeout_s must be >= 0")
        if self.desync_interval < 0:
            raise ValueError("resilience.comm.desync_interval must be >= 0")
        if self.desync_tolerance < 0:
            raise ValueError("resilience.comm.desync_tolerance must be >= 0")
        return self


class ResilienceConfig(ConfigModel):
    """``resilience`` subtree (deepspeed_tpu/resilience/): fault-tolerance
    knobs for checkpoint hardening, restart supervision, and training
    guards."""

    # elastic-agent restart budget + backoff between hard-failure restarts
    max_restarts: int = 10
    backoff_base_s: float = 1.0
    backoff_cap_s: float = 60.0
    # checkpoint GC: keep the newest k committed tags (0 = keep all).
    # GC never deletes the only structurally-verified tag.
    keep_last_k: int = 0
    # abort after this many CONSECUTIVE overflow-skipped steps (0 = off;
    # enabling costs one scalar device sync per step)
    max_consecutive_skips: int = 0
    # N > 0: fold the fused inf/nan gradient sweep into bf16/fp32 steps
    # too (fp16 loss-scaling always has it) — non-finite steps are
    # SKIPPED and N consecutive ones raise GradientAnomalyError instead
    # of silently training on NaNs.  Costs one scalar sync per step.
    check_grad_finite: int = 0
    # verify manifest byte-lengths + crc32 checksums at load; corrupt tags
    # quarantine to <tag>.corrupt and load falls back to the newest
    # verified tag
    verify_on_load: bool = True
    # silent-data-corruption defense for the NVMe moment stream
    sdc: SdcConfig = Field(default_factory=SdcConfig)
    # distributed-health knobs (collective watchdog, desync detection,
    # straggler telemetry)
    comm: CommResilienceConfig = Field(default_factory=CommResilienceConfig)

    @model_validator(mode="after")
    def _validate(self):
        if self.max_restarts < 0:
            raise ValueError("resilience.max_restarts must be >= 0")
        if self.keep_last_k < 0:
            raise ValueError("resilience.keep_last_k must be >= 0")
        if self.check_grad_finite < 0:
            raise ValueError("resilience.check_grad_finite must be >= 0")
        return self


class ElasticityConfig(ConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True
    model_parallel_size: int = 1
    num_gpus_per_node: int = 1


class CurriculumParams(ConfigModel):
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)


class DataEfficiencyConfig(ConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_routing: Dict[str, Any] = Field(default_factory=dict)
    data_sampling: Dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Top-level config
# ---------------------------------------------------------------------------

ADAM_OPTIMIZERS = ["adam", "adamw", "fusedadam"]


class DeepSpeedConfig(ConfigModel):
    """Top-level typed config (reference ``runtime/config.py:707``).

    Parameters
    ----------
    config: dict | str path to JSON
    world_size: data-parallel world size used for batch reconciliation
      (``dp_world_size`` in the reference engine).
    """

    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    steps_per_print: int = 10
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    communication_data_type: Optional[str] = None
    seed: int = 1234
    disable_allgather: bool = False
    dump_state: bool = False
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dataloader_drop_last: bool = False
    sparse_gradients: bool = False

    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = Field(default_factory=ZeroConfig)
    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None

    tensor_parallel: TensorParallelConfig = Field(default_factory=TensorParallelConfig)
    pipeline: PipelineParallelConfig = Field(default_factory=PipelineParallelConfig)
    sequence_parallel: SequenceParallelConfig = Field(default_factory=SequenceParallelConfig)
    expert_parallel: ExpertParallelConfig = Field(default_factory=ExpertParallelConfig)

    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    monitor_config: MonitorConfig = Field(default_factory=MonitorConfig)
    tensorboard: Optional[TensorBoardConfig] = None  # legacy top-level spelling
    wandb: Optional[WandbConfig] = None
    csv_monitor: Optional[CSVConfig] = None
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    aio: AioConfig = Field(default_factory=AioConfig)
    data_types: DataTypesConfig = Field(default_factory=DataTypesConfig)
    compression_training: CompressionConfig = Field(default_factory=CompressionConfig)
    elasticity: ElasticityConfig = Field(default_factory=ElasticityConfig)
    resilience: ResilienceConfig = Field(default_factory=ResilienceConfig)
    curriculum_learning: CurriculumParams = Field(default_factory=CurriculumParams)
    data_efficiency: DataEfficiencyConfig = Field(default_factory=DataEfficiencyConfig)

    load_universal_checkpoint: bool = False
    zero_allow_untested_optimizer: bool = True
    zero_force_ds_cpu_optimizer: bool = False
    graph_harvesting: bool = False  # GPU-only (cuda graphs); accepted & ignored

    # -- non-pydantic attrs populated by ``parse`` ------------------------------

    def __init__(self, **data: Any):
        super().__init__(**data)
        # legacy top-level monitor keys fold into monitor_config
        if self.tensorboard is not None:
            self.monitor_config.tensorboard = self.tensorboard
        if self.wandb is not None:
            self.monitor_config.wandb = self.wandb
        if self.csv_monitor is not None:
            self.monitor_config.csv_monitor = self.csv_monitor

    # ------------------------------------------------------------------

    @property
    def zero_enabled(self) -> bool:
        return self.zero_optimization.stage > 0

    @property
    def precision_dtype(self) -> str:
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"

    def reconcile_batch_size(self, dp_world_size: int) -> None:
        """Solve ``train = micro * gas * dp`` (reference
        ``_configure_train_batch_size``). Any two of the three determine the
        third; one alone assumes the others default; none defaults micro=1,
        gas=1.
        """
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * dp_world_size)
        elif train is not None and gas is not None:
            micro = train // (gas * dp_world_size)
        elif micro is not None and gas is not None:
            train = micro * gas * dp_world_size
        elif train is not None:
            gas = 1
            micro = train // dp_world_size
        elif micro is not None:
            gas = 1
            train = micro * dp_world_size
        else:
            micro, gas = 1, 1
            train = dp_world_size

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas
        self._batch_assertion(dp_world_size)

    def _batch_assertion(self, dp_world_size: int) -> None:
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        assert train > 0, f"train_batch_size: {train} must be > 0"
        assert micro > 0, f"train_micro_batch_size_per_gpu: {micro} must be > 0"
        assert gas > 0, f"gradient_accumulation_steps: {gas} must be > 0"
        assert train == micro * gas * dp_world_size, (
            f"Check batch related parameters: train_batch_size={train} has to equal "
            f"micro_batch_per_gpu({micro}) * gradient_acc_steps({gas}) * "
            f"dp_world_size({dp_world_size})")

    def print_config(self, name: str = "DeepSpeedConfig") -> None:
        logger.info(f"{name}:")
        logger.info(json.dumps(self.model_dump(), indent=2, default=str, sort_keys=True))


def load_config(config: Union[str, Dict[str, Any], DeepSpeedConfig, None],
                dp_world_size: Optional[int] = None) -> DeepSpeedConfig:
    """Parse a config dict / JSON path into a ``DeepSpeedConfig``."""
    if config is None:
        config = {}
    if isinstance(config, DeepSpeedConfig):
        cfg = config
    elif isinstance(config, str):
        if not os.path.exists(config):
            raise FileNotFoundError(f"DeepSpeed config path does not exist: {config}")
        with open(config) as f:
            cfg = DeepSpeedConfig(**json.load(f))
    elif isinstance(config, dict):
        cfg = DeepSpeedConfig(**config)
    else:
        raise TypeError(f"Unsupported config type: {type(config)}")
    if cfg.elasticity.enabled and dp_world_size is not None:
        _apply_elasticity(cfg, dp_world_size)
    if dp_world_size is not None:
        cfg.reconcile_batch_size(dp_world_size)
    warn_unimplemented(cfg)
    return cfg


def _apply_elasticity(cfg: DeepSpeedConfig, dp_world_size: int) -> None:
    """Elastic mode takes over the batch triple (reference
    ``runtime/config.py:735-796``): solve for the (batch, chip menu,
    micro) triple, validate the current world size against the menu, and
    override whatever batch parameters the user wrote."""
    from deepspeed_tpu.elasticity import (ElasticityConfigError,
                                          compute_elastic_config,
                                          ensure_immutable_elastic_config)

    edict = cfg.elasticity.model_dump()
    user_batch_keys = [
        k for k, v in (("train_batch_size", cfg.train_batch_size),
                       ("train_micro_batch_size_per_gpu",
                        cfg.train_micro_batch_size_per_gpu),
                       ("gradient_accumulation_steps",
                        cfg.gradient_accumulation_steps)) if v is not None]
    if user_batch_keys and not cfg.elasticity.ignore_non_elastic_batch_info:
        raise ElasticityConfigError(
            f"batch parameters {user_batch_keys} are controlled by elastic "
            "training and will not be used; set "
            "elasticity.ignore_non_elastic_batch_info=true to silence")
    ensure_immutable_elastic_config(edict)

    world = dp_world_size * max(cfg.elasticity.model_parallel_size, 1)
    batch, menu, micro = compute_elastic_config(
        {"elasticity": edict}, world_size=world)
    gas = batch // (micro * dp_world_size)
    for key, new in (("train_batch_size", batch),
                     ("train_micro_batch_size_per_gpu", micro),
                     ("gradient_accumulation_steps", gas)):
        old = getattr(cfg, key)
        if old is not None and old != new:
            logger.warning(f"[Elasticity] overriding {key}: {old} -> {new}")
        setattr(cfg, key, new)


# Reference knobs accepted for config compatibility whose BEHAVIOR is owned
# by XLA/GSPMD on TPU — tuning them cannot have an effect by design (unlike
# unimplemented features, which warn loudly below).  Grouped by what owns
# them now; surfaced once at info level when a user explicitly sets one.
_XLA_OWNED_KNOBS = {
    "bucketing/overlap (XLA schedules and fuses collectives)": (
        "allgather_bucket_size", "reduce_bucket_size", "overlap_comm",
        "allgather_partitions", "contiguous_gradients",
        "round_robin_gradients", "stage3_prefetch_bucket_size",
        "stage3_max_reuse_distance", "sub_group_size"),
    "host-memory management (jax owns pinned staging)": (
        "pin_memory", "buffer_count", "buffer_size", "max_in_cpu",
        "fast_init"),
    "cuda-graph/stream controls": ("graph_harvesting",),
    "sparse embedding-gradient allreduce (XLA AD emits dense grads; "
    "sparse scatter-grads don't map to static-shape SPMD)": (
        "sparse_gradients",),
}


def _inert_knob_notes(cfg: DeepSpeedConfig) -> list:
    set_fields = set(cfg.zero_optimization.model_fields_set) | \
        set(cfg.model_fields_set)
    # host-memory knobs live on the offload sub-models
    for sub in (cfg.zero_optimization.offload_param,
                cfg.zero_optimization.offload_optimizer):
        if sub is not None:
            set_fields |= set(sub.model_fields_set)
    notes = []
    for reason, knobs in _XLA_OWNED_KNOBS.items():
        hit = sorted(set(knobs) & set_fields)
        if hit:
            notes.append(f"{', '.join(hit)} — {reason}")
    return notes


def warn_unimplemented(cfg: DeepSpeedConfig) -> None:
    """Accepted-but-not-yet-implemented knobs fail LOUDLY instead of
    silently doing nothing (reference configs keep loading; the user keeps
    an accurate mental model).  Entries leave this list as the features
    land."""
    notes = []
    if any(getattr(cfg.compression_training, f) for f in
           ("weight_quantization", "activation_quantization",
            "sparse_pruning", "row_pruning", "head_pruning",
            "channel_pruning", "layer_reduction")):
        notes.append("compression_training.* (use deepspeed_tpu."
                     "compression.init_compression explicitly)")
    offl_p = cfg.zero_optimization.offload_param
    offl_o = cfg.zero_optimization.offload_optimizer
    if offl_p is not None and offl_p.device == "nvme":
        notes.append("offload_param.device=nvme (device=cpu pinned-host "
                     "offload IS supported)")
    # offload_optimizer.device=nvme is implemented (NVMe-swapped Adam
    # moments, runtime/swap_tensor.py); eligibility beyond the config —
    # adam-family optimizer, single controller — is checked by the engine.
    if (cfg.zero_optimization.zero_quantized_weights or
            cfg.zero_optimization.zero_quantized_gradients or
            cfg.zero_optimization.zero_quantized_nontrainable_weights):
        logger.warning(
            "config: zero_quantized_weights/gradients have no automatic "
            "engine wiring on TPU (GSPMD owns the train-step collectives); "
            "the qwZ/qgZ wire primitives are available as "
            "deepspeed_tpu.comm.quantized_all_gather / "
            "quantized_reduce_scatter inside shard_map code")
    if cfg.data_efficiency.enabled:
        logger.warning(
            "config: data_efficiency has no automatic engine wiring on "
            "TPU; use deepspeed_tpu.data_pipeline explicitly "
            "(DeepSpeedDataSampler for curriculum data_sampling, "
            "RandomLayerTokenDrop + RandomLTDScheduler for data_routing)")
    for note in notes:
        logger.warning(f"config: {note} is NOT implemented on TPU yet; "
                       "the setting has no effect")
    inert = _inert_knob_notes(cfg)
    if inert:
        logger.info("config: accepted knobs with no TPU-side effect "
                    "(the compiler owns this behavior): " +
                    "; ".join(inert))
