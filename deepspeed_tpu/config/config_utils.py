"""Typed config base class.

Re-creation of the reference's pydantic base ``DeepSpeedConfigModel``
(``deepspeed/runtime/config_utils.py:17``): JSON-compatible field names,
``"auto"`` sentinel support, deprecated-field aliasing, and strict unknown-key
warnings rather than hard failures (so reference configs keep loading even
when a knob is GPU-only and ignored on TPU).
"""
from __future__ import annotations

from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, model_validator

from deepspeed_tpu.utils.logging import logger

AUTO = "auto"


class ConfigModel(BaseModel):
    """Base for all config subtrees.

    Unknown keys are allowed (collected into ``model_extra``) and warned
    about, matching the reference's tolerance for fields consumed by other
    layers.  The check runs as a model validator so it fires for nested
    subtrees validated by pydantic directly (a custom ``__init__`` would
    not).
    """

    model_config = ConfigDict(
        extra="allow",
        populate_by_name=True,
        validate_assignment=True,
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    @model_validator(mode="after")
    def _warn_unknown_keys(self):
        if self.model_extra:
            unknown = sorted(self.model_extra.keys())
            logger.warning(f"{self.__class__.__name__}: ignoring unknown "
                           f"config keys {unknown}")
        return self

    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def to_dict(self) -> Dict[str, Any]:
        return self.model_dump()


def get_scalar_param(d: Dict[str, Any], name: str, default: Any) -> Any:
    """Reference-style helper (``runtime/config.py`` get_* functions)."""
    return d.get(name, default)
