"""Tensor parallelism (Megatron-style, GSPMD-expressed).

TPU-native re-design of the reference's TP stack:
- Megatron-style external-mpu TP (``deepspeed/utils/groups.py:187
  _create_model_parallel``) and training-time AutoTP
  (``deepspeed/__init__.py:369 tp_model_init``,
  ``runtime/tensor_parallel/tp_manager.py:12``),
- inference AutoTP (``module_inject/auto_tp.py:192`` policy-free sharding).

On TPU there are no hand-written all-reduces: a TP layer is a parameter
*sharding annotation* on the ``tensor`` mesh axis, and XLA/GSPMD inserts the
Megatron collectives (all-reduce after row-parallel matmuls, all-gather
where needed) — laid out over ICI because ``tensor`` is the innermost mesh
axis.  Column-parallel = output dim sharded; row-parallel = input dim
sharded; biases follow the output dim; norms replicate.

Three entry points:
- flax init wrappers (:func:`column_parallel_init` etc.) for models built
  TP-aware from day one (models/gpt2.py, models/llama.py use these),
- :func:`auto_tp_specs` — AutoTP equivalent: infer per-leaf PartitionSpecs
  from parameter names/shapes for models with no annotations,
- :func:`extract_partition_specs` / :func:`unbox_params` — pull flax
  ``nn.Partitioned`` metadata out of an init'd param tree for the engine.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import TENSOR_AXIS
from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# flax init wrappers (model-side annotations)
# ---------------------------------------------------------------------------

def column_parallel_init(init_fn: Callable) -> Callable:
    """Kernel (in, out) with the OUTPUT dim sharded over ``tensor``."""
    return nn.with_partitioning(init_fn, (None, TENSOR_AXIS))


def row_parallel_init(init_fn: Callable) -> Callable:
    """Kernel (in, out) with the INPUT dim sharded over ``tensor``; GSPMD
    all-reduces the partial outputs (Megatron g operator)."""
    return nn.with_partitioning(init_fn, (TENSOR_AXIS, None))


def column_parallel_bias_init(init_fn: Callable) -> Callable:
    return nn.with_partitioning(init_fn, (TENSOR_AXIS,))


def embed_parallel_init(init_fn: Callable) -> Callable:
    """Embedding (vocab, embd) sharded on the embedding dim (safer default
    than vocab sharding: no masked-gather/psum dance for out-of-shard ids)."""
    return nn.with_partitioning(init_fn, (None, TENSOR_AXIS))


def vocab_parallel_init(init_fn: Callable) -> Callable:
    """Embedding (vocab, embd) sharded on the vocab dim (Megatron
    VocabParallelEmbedding); GSPMD emits the masked-lookup + psum."""
    return nn.with_partitioning(init_fn, (TENSOR_AXIS, None))


def tp_dense_kwargs(enabled: bool, kind: str,
                    with_bias: bool = False) -> Dict[str, Any]:
    """nn.Dense init kwargs for a Megatron-TP layer ('col' or 'row').
    Shared by the model zoo so the annotation policy lives in one place."""
    if not enabled:
        return {}
    kinit = nn.initializers.lecun_normal()
    if kind == "col":
        kw: Dict[str, Any] = {"kernel_init": column_parallel_init(kinit)}
        if with_bias:
            kw["bias_init"] = column_parallel_bias_init(
                nn.initializers.zeros_init())
        return kw
    assert kind == "row", kind
    return {"kernel_init": row_parallel_init(kinit)}
    # row-parallel bias replicates (added after the all-reduce)


def tp_embed_kwargs(enabled: bool) -> Dict[str, Any]:
    """nn.Embed init kwargs sharding the embedding dim; matches flax's
    default embed initializer exactly so TP and non-TP models start from
    identical weights."""
    if not enabled:
        return {}
    return {"embedding_init": embed_parallel_init(
        nn.initializers.variance_scaling(1.0, "fan_in", "normal",
                                         out_axis=0))}


# ---------------------------------------------------------------------------
# Param-tree metadata extraction (engine-side)
# ---------------------------------------------------------------------------

def _is_boxed(leaf) -> bool:
    return isinstance(leaf, nn.Partitioned)


def has_partitioning(params) -> bool:
    return any(_is_boxed(l) for l in jax.tree_util.tree_leaves(
        params, is_leaf=_is_boxed))

def extract_partition_specs(params, mesh_axis_names: Sequence[str]):
    """Tree of PartitionSpec from flax ``Partitioned`` metadata; names that
    are not mesh axes (e.g. the nn.scan ``layers`` dimension) become None."""

    def spec_of(leaf):
        if _is_boxed(leaf):
            names = leaf.names
            return P(*(n if n in mesh_axis_names else None for n in names))
        return P()

    return jax.tree_util.tree_map(spec_of, params, is_leaf=_is_boxed)


def unbox_params(params):
    """Strip flax metadata boxes, leaving raw arrays."""
    return jax.tree_util.tree_map(
        lambda l: l.unbox() if _is_boxed(l) else l, params, is_leaf=_is_boxed)


# ---------------------------------------------------------------------------
# AutoTP: infer specs from names/shapes (module_inject/auto_tp.py analogue)
# ---------------------------------------------------------------------------

# Reference AutoTP classifies linears into "all-reduce" (row-parallel: the
# layer whose output needs summing — attention out-proj, MLP down-proj) vs
# sharded-output (column-parallel), by module name.  Same policy, on names.
_ROW_PATTERNS = (
    # w2 is the Mixtral/LLaMA-style down projection (reference
    # module_inject/auto_tp.py maps it to the all-reduce linear)
    r"(^|/)(o_proj|out_proj|dense_4h_to_h|down_proj|c_proj|wo|w2|"
    r"proj_out)(/|$)",
    r"(^|/)(attention/dense|self_attention/dense)(/|$)",
)
_COL_PATTERNS = (
    r"(^|/)(q_proj|k_proj|v_proj|qkv|c_attn|query_key_value|gate_proj|"
    r"up_proj|dense_h_to_4h|c_fc|wi|w1|w3|in_proj|lm_head)(/|$)",
)
_EMBED_PATTERNS = (r"(^|/)(wte|embed_tokens|word_embeddings|embedding|"
                   r"embed)(/|$)",)


def _path_str(kp) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)


def auto_tp_specs(params, tp_size: int,
                  mesh_axis: str = TENSOR_AXIS) -> Any:
    """Infer TP PartitionSpecs for an un-annotated param tree by name.

    2D kernels matching row/column patterns are sharded on the input/output
    dim respectively; embeddings on the embedding dim; 1D leaves following a
    column-parallel kernel shard if divisible; everything else replicates.
    Dims that don't divide ``tp_size`` replicate with a warning (the
    reference's ``get_shard_size_list`` supports uneven shards; XLA requires
    even, so we fall back to replication instead).
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs: Dict[str, P] = {}
    for kp, leaf in flat:
        path = _path_str(kp).lower()
        leaf_name = path.rsplit("/", 1)[-1]
        shape = np.shape(leaf)
        is_row = any(re.search(p, path) for p in _ROW_PATTERNS)
        is_col = any(re.search(p, path) for p in _COL_PATTERNS)
        is_embed = any(re.search(p, path) for p in _EMBED_PATTERNS)

        def _shard(dim: int) -> Optional[P]:
            if shape[dim] % tp_size == 0:
                s = [None] * len(shape)
                s[dim] = mesh_axis
                return P(*s)
            logger.warning(
                f"auto_tp: {path} {shape} dim {dim} not divisible by "
                f"tp={tp_size}; replicating")
            return None

        got = None
        if leaf_name in ("kernel", "weight", "w") and len(shape) >= 2:
            # kernels are (..., in, out) — a leading scan-layer dim is fine.
            # "weight"/"w" cover trees converted from torch state dicts.
            if is_row:
                got = _shard(-2)
            elif is_col:
                got = _shard(-1)
        elif leaf_name in ("w1", "w2", "w3") and len(shape) >= 3:
            # stacked expert tensors [E, in, out] (MoE layers store the
            # whole expert bank as one leaf): w1/w3 are column-parallel
            # (output dim), w2 row-parallel (input dim) — the reference's
            # MoE TP policy (module_inject auto_tp w1/w3 vs w2)
            got = _shard(-2) if leaf_name == "w2" else _shard(-1)
        elif leaf_name in ("bias", "b") and shape:
            # column-parallel biases follow the sharded output; row-parallel
            # biases are added after the all-reduce and must replicate
            if is_col:
                got = _shard(-1)
        elif leaf_name in ("embedding", "weight") and len(shape) >= 2 \
                and is_embed:
            got = _shard(-1)
        elif (is_row or is_col) and len(shape) >= 2:
            logger.warning(
                f"auto_tp: {path} {shape} matches a TP pattern but leaf name "
                f"{leaf_name!r} is not recognised; replicating")
        specs[_path_str(kp)] = got or P()

    return jax.tree_util.tree_map_with_path(
        lambda kp, _: specs[_path_str(kp)], params)
