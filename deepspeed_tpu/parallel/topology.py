"""Device-mesh topology.

TPU-native replacement for the reference's process-group bookkeeping
(``deepspeed/utils/groups.py`` and ``deepspeed/runtime/pipe/topology.py``).
Instead of building many ``torch.distributed`` process groups, a single
``jax.sharding.Mesh`` carries every parallelism axis; "groups" become mesh
axis names.  Axis order (outer→inner) is chosen so the innermost axes map to
ICI-adjacent devices (tensor/seq innermost, pipe outermost — matching how
DCN/ICI should be assigned on multi-slice):

    ("pipe", "data", "expert", "seq", "tensor")

- ``data``   — DP / ZeRO sharding axis (reference ``_create_expert_and_data_parallel``)
- ``expert`` — expert parallelism; divides what would otherwise be data
  (reference expert groups are subgroups of DP, ``groups.py:236``)
- ``seq``    — Ulysses/ring sequence parallelism (``groups.py:611``)
- ``tensor`` — Megatron-style TP (``groups.py:187 _create_model_parallel``)
- ``pipe``   — pipeline stages (``runtime/pipe/topology.py``)

ZeRO partitions over the combined (data, expert, seq) extent mirroring the
reference's ``seq_data_parallel_group`` (engine.py:1603).
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from deepspeed_tpu.utils.logging import log_dist

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
# hpZ / ZeRO++ secondary-partition sub-axis (reference
# ``groups.py:650 _create_zero_param_parallel_group``): the data axis splits
# into data (across-node, outer) x data_sub (node-local, inner, size
# ``zero_hpz_partition_size``); stage-3 params shard only over ``data_sub``
# so their all-gathers ride node-local ICI, while grads/optimizer state
# shard over the full data x data_sub extent.  Size 1 (no hpZ) by default.
HPZ_AXIS = "data_sub"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
TENSOR_AXIS = "tensor"

AXIS_ORDER: Tuple[str, ...] = (PIPE_AXIS, DATA_AXIS, HPZ_AXIS, EXPERT_AXIS,
                               SEQ_AXIS, TENSOR_AXIS)


class MeshTopology:
    """One mesh, every parallelism axis.

    Parameters mirror the reference's sizes: ``pp`` pipeline stages, ``tp``
    tensor-parallel degree, ``sp`` sequence-parallel degree, ``ep`` expert
    parallel degree; ``dp`` is inferred from the device count unless given.
    """

    def __init__(self,
                 dp: Optional[int] = None,
                 tp: int = 1,
                 pp: int = 1,
                 sp: int = 1,
                 ep: int = 1,
                 hpz: int = 1,
                 devices: Optional[Sequence[jax.Device]] = None):
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        denom = tp * pp * sp * ep
        if n % denom != 0:
            raise ValueError(
                f"device count {n} not divisible by tp*pp*sp*ep = {denom}")
        inferred_dp = n // denom
        if dp is None:
            dp = inferred_dp
        if dp * denom != n:
            raise ValueError(
                f"dp({dp}) * tp({tp}) * pp({pp}) * sp({sp}) * ep({ep}) != "
                f"device count {n}")
        if dp % hpz != 0:
            raise ValueError(f"dp({dp}) not divisible by hpz({hpz})")
        self.shape: Dict[str, int] = {
            PIPE_AXIS: pp, DATA_AXIS: dp // hpz, HPZ_AXIS: hpz,
            EXPERT_AXIS: ep, SEQ_AXIS: sp, TENSOR_AXIS: tp,
        }
        dev_array = np.asarray(devices).reshape(
            tuple(self.shape[a] for a in AXIS_ORDER))
        self.mesh = Mesh(dev_array, AXIS_ORDER)
        log_dist(f"MeshTopology: {self.describe()}", ranks=[0])

    # -- sizes ----------------------------------------------------------

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.shape.values())))

    def axis_size(self, axis: str) -> int:
        return self.shape[axis]

    @property
    def data_parallel_size(self) -> int:
        return self.shape[DATA_AXIS] * self.shape[HPZ_AXIS]

    @property
    def hpz_partition_size(self) -> int:
        return self.shape[HPZ_AXIS]

    @property
    def tensor_parallel_size(self) -> int:
        return self.shape[TENSOR_AXIS]

    @property
    def pipe_parallel_size(self) -> int:
        return self.shape[PIPE_AXIS]

    @property
    def sequence_parallel_size(self) -> int:
        return self.shape[SEQ_AXIS]

    @property
    def expert_parallel_size(self) -> int:
        return self.shape[EXPERT_AXIS]

    # -- derived groups (axis-name tuples usable in shard_map/psum) ------

    @property
    def zero_axes(self) -> Tuple[str, ...]:
        """Axes ZeRO partitions over: data × expert × seq (the reference's
        ``seq_data_parallel_group``; expert params handle ``expert``
        separately via :meth:`expert_zero_axes`)."""
        return (DATA_AXIS, HPZ_AXIS, EXPERT_AXIS, SEQ_AXIS)

    @property
    def expert_zero_axes(self) -> Tuple[str, ...]:
        """Axes expert params ZeRO-shard over (the reference's
        ``expert_data_parallel_group``)."""
        return (DATA_AXIS, HPZ_AXIS, SEQ_AXIS)

    @property
    def grad_reduce_axes(self) -> Tuple[str, ...]:
        """Axes over which dense-param gradients are averaged."""
        return (DATA_AXIS, HPZ_AXIS, EXPERT_AXIS, SEQ_AXIS)

    @property
    def expert_grad_reduce_axes(self) -> Tuple[str, ...]:
        return (DATA_AXIS, HPZ_AXIS, SEQ_AXIS)

    def zero_partition_count(self) -> int:
        return int(np.prod([self.shape[a] for a in self.zero_axes]))

    # -- misc ------------------------------------------------------------

    def describe(self) -> str:
        return " x ".join(f"{a}={s}" for a, s in self.shape.items())

    def __repr__(self) -> str:  # pragma: no cover
        return f"MeshTopology({self.describe()})"


class ProcessCoord:
    """Named coordinate in the topology (reference ``topology.py``
    ``ProcessCoord`` namedtuple equivalent)."""

    def __init__(self, **kwargs: int):
        self.coords = dict(kwargs)

    def __getattr__(self, item):
        try:
            return self.coords[item]
        except KeyError as e:  # pragma: no cover
            raise AttributeError(item) from e

    def __repr__(self):  # pragma: no cover
        return f"ProcessCoord({self.coords})"


class ProcessTopology:
    """Axis/coordinate bookkeeping for rank↔coordinate mapping.

    Pure-python mirror of ``runtime/pipe/topology.py:ProcessTopology``; used
    by the pipeline module partitioner and the checkpoint resharder, where
    ranks are positions in the mesh rather than torch process-group ranks.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(dims)

    def get_rank(self, **coords: int) -> int:
        assert set(coords.keys()) == set(self.axes), \
            f"need all axes {self.axes}, got {list(coords)}"
        rank = 0
        for axis, dim in zip(self.axes, self.dims):
            c = coords[axis]
            assert 0 <= c < dim
            rank = rank * dim + c
        return rank

    def get_coord(self, rank: int) -> ProcessCoord:
        coords = {}
        for axis, dim in zip(reversed(self.axes), reversed(self.dims)):
            coords[axis] = rank % dim
            rank //= dim
        return ProcessCoord(**coords)

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def world_size(self) -> int:
        return int(math.prod(self.dims))

    def get_axis_list(self, axis: str, idx: int):
        """All ranks whose coordinate on ``axis`` equals ``idx``."""
        return [r for r in range(self.world_size())
                if getattr(self.get_coord(r), axis) == idx]


class PipeModelDataParallelTopology(ProcessTopology):
    """Reference ``topology.py:PipeModelDataParallelTopology`` with axes
    (pipe, data, model)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])
