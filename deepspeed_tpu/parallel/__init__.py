from deepspeed_tpu.parallel.topology import (
    MeshTopology,
    ProcessTopology,
    PipeModelDataParallelTopology,
    PIPE_AXIS,
    DATA_AXIS,
    EXPERT_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
    AXIS_ORDER,
)

__all__ = [
    "MeshTopology", "ProcessTopology", "PipeModelDataParallelTopology",
    "PIPE_AXIS", "DATA_AXIS", "EXPERT_AXIS", "SEQ_AXIS", "TENSOR_AXIS",
    "AXIS_ORDER",
]
