"""Pipeline parallelism, TPU-native.

Re-design of the reference pipeline engine (``runtime/pipe/module.py:86
PipelineModule``, ``runtime/pipe/schedule.py:189 TrainSchedule`` (1F1B),
``runtime/pipe/engine.py:338 PipelineEngine.train_batch``, p2p meta
handshake ``engine.py:928``).  The reference is an imperative instruction
interpreter: per-rank 1F1B instruction streams issuing torch p2p sends/recvs
between stage processes.  On TPU the whole pipeline compiles into ONE jitted
program:

- the transformer blocks become a stacked parameter tree ``[S, L/S, ...]``
  whose stage axis is annotated onto the ``pipe`` mesh axis;
- the microbatch schedule is a ``lax.scan`` over ``M + S - 1`` ticks of a
  GPipe pipeline: every tick, all S stages run in parallel (each pipe rank
  computes its stage), then the activation buffer rolls one stage forward —
  ``jnp.roll`` on a pipe-sharded axis, which XLA lowers to the
  ``collective-permute`` that ``p2p.send/recv`` does by hand;
- the backward pipeline comes from AD through the scan: reverse-order ticks
  with the transposed permute, no hand-written schedule.

Why GPipe ticks instead of literal 1F1B: 1F1B exists to bound live
activation memory in an eager runtime by interleaving hand-issued fwd/bwd
micro-steps.  Under XLA the same bound comes from ``nn.remat`` over the
stage body (stash = one stage input per in-flight microbatch) and the
schedule itself is the compiler's; the bubble fraction (S-1)/(M+S-1) is
identical.  Fill/drain ticks compute on zero buffers and are masked out of
the collected outputs — that waste IS the pipeline bubble.

Composition: batch (microbatch) dim stays sharded over ``data`` (DP/ZeRO),
parameters keep TP annotations inside each block, and ZeRO claims dims the
``pipe``/``tensor`` axes don't use — PP x DP x TP x ZeRO in one mesh.
"""
from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import DATA_AXIS, HPZ_AXIS, PIPE_AXIS
from deepspeed_tpu.utils.sharding import maybe_constrain


def apply_pipeline_specs(params, base_specs):
    """Overlay base PartitionSpecs for pipeline-stage parameters.

    Stage-stacked leaves (path contains ``ticks/stages``) get their leading
    (stage) dim sharded over ``pipe``.  Boxed (TP-annotated) leaves already
    carry the axis name via flax metadata; this covers the un-annotated
    case so PP models always stage-shard their parameters (the reference
    ``PipelineModule`` builds only the local stage's layers —
    ``pipe/module.py:86``; here the sharding achieves the same residency).
    Returns a base-spec tree (creating one if ``base_specs`` is None); the
    ZeRO plan then composes on the remaining dims.
    """
    import jax.tree_util as jtu
    from jax.sharding import PartitionSpec as P

    flat, treedef = jtu.tree_flatten_with_path(params)
    if not any("ticks/stages" in _kp_str(kp) for kp, _ in flat):
        return base_specs
    if base_specs is None:
        base_specs = jtu.tree_unflatten(treedef, [P()] * len(flat))
    spec_flat = jtu.tree_flatten(
        base_specs, is_leaf=lambda x: isinstance(x, P))[0]

    out = []
    for (kp, leaf), spec in zip(flat, spec_flat):
        if "ticks/stages" in _kp_str(kp):
            ndim = len(leaf.shape)
            s = list(spec) + [None] * (ndim - len(spec))
            used = {a for e in s if e is not None
                    for a in ((e,) if isinstance(e, str) else e)}
            if PIPE_AXIS not in used and s and s[0] is None:
                s[0] = PIPE_AXIS
            out.append(P(*s))
        else:
            out.append(spec)
    return jtu.tree_unflatten(treedef, out)


def validate_pipeline_layout(params, topology) -> None:
    """Catch stage-count/mesh mismatches at setup instead of deep inside
    GSPMD.  The reference fails equivalently in ``PipelineModule`` when
    ``num_stages`` doesn't divide the topology (``pipe/module.py:144``)."""
    import jax.tree_util as jtu

    from deepspeed_tpu.utils.logging import logger

    pp = topology.pipe_parallel_size
    stage_dims = {leaf.shape[0]
                  for kp, leaf in jtu.tree_flatten_with_path(params)[0]
                  if "ticks/stages" in _kp_str(kp)}
    if not stage_dims:
        if pp > 1:
            logger.warning(
                f"mesh has pipe={pp} but the model has no pipeline-stage "
                "parameters (pipeline_stages<=1?): the whole computation "
                "will be REPLICATED across the pipe axis, wasting "
                f"{pp - 1}/{pp} of the devices")
        return
    n_stages = max(stage_dims)
    if pp > 1 and n_stages % pp != 0:
        raise ValueError(
            f"model pipeline_stages={n_stages} is not divisible by the "
            f"mesh pipe axis size {pp}")


def _kp_str(kp) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)


class _LayerScan(nn.Module):
    """Scan-over-layers adapter: carry = (x, bcast)."""

    block_cls: Any
    block_args: Tuple

    @nn.compact
    def __call__(self, carry, _):
        x, bcast = carry
        x = self.block_cls(*self.block_args, name="block")(x, *bcast)
        return (x, bcast), None


class _Stage(nn.Module):
    """One pipeline stage: L/S sequential blocks (params [L/S, ...])."""

    block_cls: Any
    block_args: Tuple
    layers_per_stage: int
    remat_policy: str

    @nn.compact
    def __call__(self, x, *bcast):
        body = _LayerScan
        if self.remat_policy != "none":
            from deepspeed_tpu.models.gpt2 import remat_policy_fn

            body = nn.remat(_LayerScan, prevent_cse=False,
                            policy=remat_policy_fn(self.remat_policy))
        (x, _), _ = nn.scan(
            body,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=self.layers_per_stage,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(self.block_cls, self.block_args, name="layers")((x, bcast), None)
        return x


class _Tick(nn.Module):
    """One pipeline tick: run all stages, shift the activation ring."""

    block_cls: Any
    block_args: Tuple
    layers_per_stage: int
    n_stages: int
    remat_policy: str

    @nn.compact
    def __call__(self, carry, inp):
        state, bcast = carry                       # prev tick's outputs [S,..]
        # ring shift stage s -> s+1 (collective-permute over `pipe`) and
        # feed this tick's microbatch into stage 0 — shift BEFORE compute so
        # microbatch t enters stage 0 at tick t and exits at tick t + S - 1
        staged = jnp.roll(state, 1, axis=0).at[0].set(inp)
        staged = maybe_constrain(
            staged, (PIPE_AXIS, (DATA_AXIS, HPZ_AXIS)) + (None,) * (staged.ndim - 2))
        stage = nn.vmap(
            _Stage,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(0,) + (None,) * len(bcast),
            metadata_params={nn.PARTITION_NAME: PIPE_AXIS},
        )(self.block_cls, self.block_args, self.layers_per_stage,
          self.remat_policy, name="stages")
        out = stage(staged, *bcast)                # [S, mb, ...]
        out = maybe_constrain(
            out, (PIPE_AXIS, (DATA_AXIS, HPZ_AXIS)) + (None,) * (out.ndim - 2))
        return (out, bcast), out[-1]               # finished microbatch


class GPipe(nn.Module):
    """Pipeline ``n_layer`` blocks over ``n_stages`` pipe ranks with
    ``n_micro`` microbatches.  ``block_cls(*block_args)(x, *bcast) -> x``
    is one transformer block; ``bcast`` values (e.g. RoPE positions) are
    broadcast to every stage and tick.
    """

    block_cls: Any
    block_args: Tuple
    n_layer: int
    n_stages: int
    n_micro: int
    remat_policy: str = "none"

    @nn.compact
    def __call__(self, x, *bcast):
        S, M, L = self.n_stages, self.n_micro, self.n_layer
        assert L % S == 0, f"n_layer {L} not divisible by stages {S}"
        B = x.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        mb = B // M
        xm = x.reshape(M, mb, *x.shape[1:])
        T = M + S - 1                              # ticks incl. fill/drain
        inputs = jnp.concatenate(
            [xm, jnp.zeros((S - 1,) + xm.shape[1:], xm.dtype)], axis=0)

        state0 = jnp.zeros((S, mb) + x.shape[1:], x.dtype)
        state0 = maybe_constrain(
            state0, (PIPE_AXIS, (DATA_AXIS, HPZ_AXIS)) + (None,) * (state0.ndim - 2))

        (_, _), outs = nn.scan(
            _Tick,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            length=T,
        )(self.block_cls, self.block_args, L // S, S, self.remat_policy,
          name="ticks")((state0, tuple(bcast)), inputs)

        # microbatch m exits the last stage at tick m + S - 1
        out = outs[S - 1:]                         # [M, mb, ...]
        return out.reshape((B,) + out.shape[2:])
