from deepspeed_tpu.comm.comm import (
    init_distributed,
    is_initialized,
    initialize_mesh,
    set_topology,
    get_topology,
    peek_topology,
    get_mesh,
    get_world_size,
    get_rank,
    get_local_rank,
    get_process_count,
    barrier,
    all_reduce,
    inference_all_reduce,
    all_gather,
    reduce_scatter,
    all_to_all,
    ppermute,
    broadcast,
    axis_index,
    log_summary,
    straggler_report,
    configure,
    comms_logger,
)
from deepspeed_tpu.comm import watchdog
from deepspeed_tpu.comm.comms_logging import CommsLogger, get_bw
from deepspeed_tpu.comm.quantized import (quantized_all_gather,
                                          quantized_reduce_scatter)

__all__ = [
    "init_distributed", "is_initialized", "initialize_mesh", "set_topology",
    "get_topology", "peek_topology", "get_mesh", "get_world_size", "get_rank", "get_local_rank",
    "get_process_count", "barrier", "all_reduce", "inference_all_reduce",
    "all_gather", "reduce_scatter", "all_to_all", "ppermute", "broadcast",
    "axis_index", "log_summary", "straggler_report", "configure",
    "comms_logger", "CommsLogger", "watchdog",
    "quantized_all_gather", "quantized_reduce_scatter",
    "get_bw",
]
