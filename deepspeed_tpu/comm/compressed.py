"""Error-feedback 1-bit compressed all-reduce (the 1-bit Adam/LAMB wire).

TPU-native re-design of the reference compression backends
(``runtime/comm/nccl.py:51 compressed_allreduce``, ``runtime/comm/mpi.py``,
``hccl.py`` — cupy bit-packing + two-phase gather/scatter): each member

1. adds its carried ``worker_error`` to the input, takes the sign, and
   remembers the new quantization error (error feedback keeps the
   compression *unbiased over time* — the 1-bit Adam convergence result);
2. ships one SIGN BIT per element (packed 8-per-byte) plus one fp32 scale
   (||x||/sqrt(n), so sign*scale preserves the l2 norm) through an
   all-to-all: member i receives everyone's chunk i;
3. averages its chunk server-side, compresses AGAIN with its carried
   ``server_error``, and all-gathers the re-compressed chunk — both wire
   phases are 1-bit, the reference's two-phase design.

32x less traffic than fp32 all-reduce (64x vs a naive
gather-the-world), at the cost of sign-quantization noise that the twin
error accumulators feed back into the next step.

In-graph collective: call inside ``shard_map`` with the group axes in
scope.  Chunking pads to ``group_size * 8`` elements internally; inputs of
any shape are accepted and restored.
"""
from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.comm.comm import _resolve_axes, comms_logger

GroupLike = Union[None, str, Sequence[str]]

_BITS = jnp.uint8(2) ** jnp.arange(8, dtype=jnp.uint8)


def pack_signs(x: jax.Array) -> jax.Array:
    """[N] float -> [N/8] uint8 of sign bits (1 = non-negative).  N must be
    a multiple of 8."""
    bits = (x >= 0).reshape(-1, 8).astype(jnp.uint8)
    return (bits * _BITS).sum(axis=1).astype(jnp.uint8)


def unpack_signs(p: jax.Array) -> jax.Array:
    """[M] uint8 -> [M*8] float32 of {-1, +1}."""
    bits = (p[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def _scale(x: jax.Array) -> jax.Array:
    # sign*scale preserves the l2 norm of the compressed tensor
    return jnp.linalg.norm(x) / np.sqrt(x.size)


def compressed_allreduce(
        x: jax.Array, worker_error: jax.Array, server_error: jax.Array,
        group: GroupLike = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """1-bit averaged all-reduce of ``x`` with twin error feedback.

    ``worker_error``: [padded_numel] carried worker-side quantization
    error.  ``server_error``: [padded_numel / group_size] carried
    server-side error for this member's chunk.  Use
    :func:`error_shapes` to size them.  Returns ``(avg, new_worker_error,
    new_server_error)`` with ``avg`` reshaped to ``x``'s shape.
    """
    if group is None:                      # explicit no-comm (single member)
        return x, worker_error, server_error
    axes = _resolve_axes(group)
    import deepspeed_tpu.comm as dist

    topo = dist.get_topology()
    n = int(np.prod([topo.axis_size(a) for a in axes]))
    shape = x.shape
    if n == 1:
        return x, worker_error, server_error

    numel = int(np.prod(shape))
    pad = worker_error.size - numel
    flat = jnp.concatenate(
        [x.reshape(-1).astype(jnp.float32),
         jnp.zeros((pad,), jnp.float32)]) if pad else \
        x.reshape(-1).astype(jnp.float32)

    # ---- worker-side compression with error feedback ------------------
    buf = flat + worker_error
    w_scale = _scale(buf)
    signs = jnp.sign(buf)
    signs = jnp.where(signs == 0, 1.0, signs)          # sign bit is binary
    new_worker_error = buf - w_scale * signs

    chunk = buf.size // n
    packed = pack_signs(signs).reshape(n, chunk // 8)  # [n, chunk/8] uint8
    comms_logger.append("compressed_allreduce",
                        int(packed.size + 4) * 2, n, None, "1bit")

    # phase 1: member i collects everyone's chunk i + every scale
    recv = lax.all_to_all(packed, axes[0] if len(axes) == 1 else axes,
                          split_axis=0, concat_axis=0, tiled=False)
    recv = recv.reshape(n, chunk // 8)
    scales = lax.all_gather(w_scale, axes).reshape(n)

    # ---- server-side average + re-compression -------------------------
    member_chunks = jax.vmap(unpack_signs)(recv)       # [n, chunk]
    server_m = (member_chunks * scales[:, None]).mean(axis=0)
    server_m = server_m + server_error
    s_scale = _scale(server_m)
    s_signs = jnp.sign(server_m)
    s_signs = jnp.where(s_signs == 0, 1.0, s_signs)
    new_server_error = server_m - s_scale * s_signs

    # phase 2: all-gather the re-compressed server chunks
    s_packed = pack_signs(s_signs)
    all_packed = lax.all_gather(s_packed, axes).reshape(n, chunk // 8)
    all_scales = lax.all_gather(s_scale, axes).reshape(n)
    parts = jax.vmap(unpack_signs)(all_packed) * all_scales[:, None]
    out = parts.reshape(-1)[:numel].reshape(shape).astype(x.dtype)
    return out, new_worker_error, new_server_error


def error_shapes(numel: int, group_size: int) -> Tuple[int, int]:
    """(worker_error_numel, server_error_numel) for a tensor of ``numel``
    elements reduced over a ``group_size``-member group: padded so every
    member's chunk is a whole number of packed bytes."""
    unit = group_size * 8
    padded = ((numel + unit - 1) // unit) * unit
    return padded, padded // group_size
