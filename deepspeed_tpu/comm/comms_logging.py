"""Communication op logging.

Re-creation of the reference's ``deepspeed/utils/comms_logging.py:67``
(``CommsLogger``) and the bus-bandwidth math in ``get_bw``: every collective
issued through the ``deepspeed_tpu.comm`` facade is recorded (op name,
message size, world size, latency when measurable) and ``log_summary``
prints the per-op table with algorithmic and bus bandwidth plus an optional
straggler effect (max-latency vs avg-latency difference across calls).

Under ``jit`` individual collectives cannot be wall-clock timed from the
host (XLA fuses and overlaps them); those records carry ``latency=None`` and
the summary reports counts/volumes only — per-op device timing belongs to
the profiler (``jax.profiler`` traces).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


def get_bw(comm_op: str, size_bytes: int, duration_s: float, n: int) -> Dict[str, float]:
    """Algorithmic / bus bandwidth in GB/s (reference ``get_bw``)."""
    if duration_s <= 0:
        return {"algbw": 0.0, "busbw": 0.0}
    tput = size_bytes / duration_s
    if comm_op in ("all_to_all", "all_to_all_single", "all_gather",
                   "all_gather_into_tensor", "reduce_scatter",
                   "reduce_scatter_tensor"):
        busbw = tput * ((n - 1) / n) if n > 0 else tput
    elif comm_op in ("all_reduce",):
        busbw = tput * (2 * (n - 1) / n) if n > 0 else tput
    elif comm_op in ("send", "recv", "isend", "irecv", "broadcast", "reduce",
                     "gather", "scatter", "barrier", "ppermute"):
        busbw = tput
    else:
        busbw = tput
    return {"algbw": tput / 1e9, "busbw": busbw / 1e9}


def calc_bw_log(comm_op: str, size: int, duration: float, n: int):
    bws = get_bw(comm_op, size, duration, n)
    return bws["algbw"], bws["busbw"]


def convert_size(size_bytes: int) -> str:
    if size_bytes == 0:
        return "0B"
    names = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


class CommsLogger:
    """Per-op record book (reference ``CommsLogger``)."""

    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, debug: bool = False,
                 prof_ops: Optional[List[str]] = None):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        self.prof_ops = prof_ops or []
        # op_name -> msg_size -> [count, total_lat, [lats...], world]
        self.comms_dict: Dict[str, Dict[int, list]] = {}

    def configure(self, config) -> None:
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.debug = config.debug
        self.prof_ops = list(config.prof_ops)

    def should_profile(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        if self.prof_ops:
            return op_name in self.prof_ops
        return self.prof_all

    def append(self, op_name: str, size: int, world: int,
               latency: Optional[float] = None, log_name: Optional[str] = None) -> None:
        if not self.should_profile(op_name):
            return
        key = log_name or op_name
        per_op = self.comms_dict.setdefault(key, {})
        rec = per_op.setdefault(size, [0, 0.0, [], world])
        rec[0] += 1
        if latency is not None:
            rec[1] += latency
            rec[2].append(latency)
        rec[3] = world
        if self.verbose:
            if latency is not None:
                algbw, busbw = calc_bw_log(op_name, size, latency, world)
                logger.info(
                    f"comm op: {key} | time (ms): {latency * 1000:.2f} | "
                    f"msg size: {convert_size(size)} | algbw (Gbps): {algbw * 8:.2f} | "
                    f"busbw (Gbps): {busbw * 8:.2f}")
            else:
                logger.info(f"comm op: {key} (traced) | msg size: {convert_size(size)} "
                            f"| world: {world}")

    def per_op_mean_latency(self) -> Dict[str, Dict[str, float]]:
        """``{op: {"mean_s", "count"}}`` over every measured (eager)
        call, all message sizes pooled — the local half of the
        cross-rank straggler aggregation
        (``resilience/distributed.py build_straggler_report``)."""
        out: Dict[str, Dict[str, float]] = {}
        for op_name, sizes in self.comms_dict.items():
            total, n = 0.0, 0
            for _size, (_count, total_lat, lats, _world) in sizes.items():
                total += total_lat
                n += len(lats)
            if n:
                out[op_name] = {"mean_s": total / n, "count": n}
        return out

    @staticmethod
    def render_straggler_report(report: Dict[str, Dict]) -> str:
        """Human-readable lines for a cross-rank straggler report
        (``build_straggler_report`` output): one line per op, naming
        the straggler rank when one cleared the thresholds."""
        lines = ["cross-rank straggler report:"]
        if not report:
            lines.append("  (no cross-rank timing data)")
        for op, rec in sorted(report.items()):
            per_rank = ", ".join(f"r{i}={m:.2f}" for i, m in
                                 enumerate(rec["per_rank_ms"]))
            if rec["straggler_rank"] is not None:
                lines.append(
                    f"  {op}: STRAGGLER rank {rec['straggler_rank']} — "
                    f"peers wait {rec['spread_ms']:.2f} ms for it "
                    f"(per-rank mean ms: {per_rank})")
            else:
                lines.append(f"  {op}: no straggler (spread "
                             f"{rec['spread_ms']:.2f} ms; per-rank mean "
                             f"ms: {per_rank})")
        return "\n".join(lines)

    def log_summary(self, show_straggler: bool = False) -> str:
        lines = []
        header = (f"{'Comm. Op':<25}{'Message Size':<18}{'Count':<8}"
                  f"{'Total Lat(ms)':<16}{'Avg Lat(ms)':<14}{'algbw(Gbps)':<14}"
                  f"{'busbw(Gbps)':<14}")
        lines.append(header)
        for op_name, sizes in sorted(self.comms_dict.items()):
            for size, (count, total_lat, lats, world) in sorted(sizes.items()):
                if lats:
                    avg = total_lat / len(lats)
                    algbw, busbw = calc_bw_log(op_name, size, avg, world)
                    lines.append(
                        f"{op_name:<25}{convert_size(size):<18}{count:<8}"
                        f"{total_lat * 1000:<16.2f}{avg * 1000:<14.2f}"
                        f"{algbw * 8:<14.2f}{busbw * 8:<14.2f}")
                    if show_straggler and lats:
                        worst = max(lats)
                        lines.append(f"{'':<25}{'straggler effect':<18}"
                                     f"{(worst - avg) * 1000:.2f} ms")
                else:
                    lines.append(
                        f"{op_name:<25}{convert_size(size):<18}{count:<8}"
                        f"{'traced':<16}{'-':<14}{'-':<14}{'-':<14}")
        out = "\n".join(lines)
        logger.info("\n" + out)
        return out

    def reset(self) -> None:
        self.comms_dict = {}
