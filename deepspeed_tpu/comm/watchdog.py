"""Collective watchdog: eager collectives under a deadline.

A dropped or wedged collective (peer died mid-collective, peer's rank
skipped the call, transport hang) blocks every surviving rank
indefinitely — by default the only way out is an outer harness killing
the job at ITS timeout.  The watchdog bounds that: when armed with a
deadline, each eager collective's blocking wait runs on a dedicated
heartbeat thread while the caller waits at most ``deadline_s``; on
expiry the caller gets :class:`CollectiveTimeout`
(``resilience/distributed.py``) and can abort cleanly (the engine
routes it through the preemption path; the elastic agent counts it as
a restartable hard failure).

Disabled (the default, ``deadline_s == 0``) the guard is a direct call
— no thread, no handoff, zero overhead on the fault-free path.  The
wedged heartbeat thread is abandoned on timeout (daemon — a blocked
gloo/ICI wait cannot be interrupted from Python) and a fresh one is
spawned for the next collective.

Armed via ``resilience.comm.collective_timeout_s`` in the DeepSpeed
config (the engine calls :func:`configure`) or the
``DSTPU_COLLECTIVE_TIMEOUT_S`` environment variable (workers without
an engine).
"""
from __future__ import annotations

import concurrent.futures
import os
from typing import Any, Callable, Optional

from deepspeed_tpu.resilience.distributed import CollectiveTimeout
from deepspeed_tpu.utils.logging import logger

__all__ = ["CollectiveWatchdog", "CollectiveTimeout", "configure",
           "get_watchdog", "guard"]


class CollectiveWatchdog:
    """Deadline enforcement for blocking collective waits.

    ``timeouts`` counts expiries (telemetry + test assertions).  One
    watchdog per process is the normal shape (module singleton below);
    standalone instances are fine for tests."""

    def __init__(self, deadline_s: float = 0.0):
        self.deadline_s = float(deadline_s)
        self.timeouts = 0
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None

    @property
    def enabled(self) -> bool:
        return self.deadline_s > 0

    def guard(self, fn: Callable[[], Any], what: str = "collective") -> Any:
        """Run ``fn`` (a blocking collective wait) under the deadline.

        Disabled: calls ``fn`` inline.  Enabled: runs it on the
        heartbeat thread; expiry abandons that thread and raises
        :class:`CollectiveTimeout`."""
        if not self.enabled:
            return fn()
        pool = self._pool
        if pool is None:
            pool = self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dstpu-collective-wd")
        fut = pool.submit(fn)
        try:
            return fut.result(timeout=self.deadline_s)
        except concurrent.futures.TimeoutError:
            self.timeouts += 1
            # the heartbeat thread is wedged inside the collective and
            # may never return — abandon the pool (daemon threads) and
            # let the next guarded call build a fresh one
            self._pool = None
            pool.shutdown(wait=False)
            logger.error(f"collective watchdog: {what} exceeded "
                         f"{self.deadline_s:.1f}s deadline — failing fast")
            err = CollectiveTimeout(
                f"{what} exceeded the {self.deadline_s:.1f}s collective "
                "deadline (a peer rank dropped the collective, died "
                "mid-collective, or the transport wedged); "
                "resilience.comm.collective_timeout_s bounds this wait")
            from deepspeed_tpu.telemetry import flight
            from deepspeed_tpu.telemetry.metrics import metrics as _metrics

            if _metrics.enabled:
                _metrics.counter(
                    "dstpu_watchdog_timeouts_total",
                    "Collective watchdog deadline fires",
                    labels=("what",)).labels(what=what).inc()
            flight.dump_on_fault("collective_timeout", err,
                                 extra={"what": what,
                                        "deadline_s": self.deadline_s})
            raise err from None


_WATCHDOG = CollectiveWatchdog(
    float(os.environ.get("DSTPU_COLLECTIVE_TIMEOUT_S", "0") or 0))


def configure(deadline_s: float) -> None:
    """Set the process-wide collective deadline (0 disables)."""
    _WATCHDOG.deadline_s = float(deadline_s)


def get_watchdog() -> CollectiveWatchdog:
    return _WATCHDOG


def guard(fn: Callable[[], Any], what: str = "collective") -> Any:
    return _WATCHDOG.guard(fn, what)
