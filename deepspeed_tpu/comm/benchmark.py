"""Collective micro-benchmark (``ds_bench`` equivalent).

Reference: ``bin/ds_bench`` + the DeepSpeedExamples communication
benchmarks — time each collective across message sizes and report
algorithmic + bus bandwidth.  Here the collectives are the eager facade
ops (``deepspeed_tpu.comm``), timed with device synchronization, and
busbw uses the same formulas as ``comms_logging.get_bw``.

Run: ``python -m deepspeed_tpu.comm.benchmark [--ops all_reduce ...]
[--maxsize 26]`` (sizes are powers of two bytes, fp32 elements).
"""
from __future__ import annotations

import argparse
import time
from typing import Dict, List

import jax
import numpy as np

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm.comms_logging import get_bw

OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
       "broadcast")


def _run_op(op: str, x, group):
    fn = getattr(dist, op)
    if op == "broadcast":
        return fn(x, src=0, group=group)
    return fn(x, group=group)


def time_collective(op: str, nbytes: int, group=None, trials: int = 20,
                    warmups: int = 5) -> Dict[str, float]:
    # the group the op actually runs over (default = all non-trivial axes)
    world = dist.get_world_size(group)
    # eager facade contract: leading dim = group size (one slice/member);
    # ``nbytes`` is the PER-MEMBER payload (the ds_bench per-rank
    # message-size convention, so numbers compare with the reference)
    n = max(nbytes // 4, 1)
    x = jax.device_put(np.ones((world, n), np.float32))
    for _ in range(warmups):
        out = _run_op(op, x, group)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(trials):
        out = _run_op(op, x, group)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / trials
    bws = get_bw(op, nbytes, dt, world)       # already GB/s
    return {"size_bytes": nbytes, "latency_us": dt * 1e6,
            "algbw_gbps": bws["algbw"], "busbw_gbps": bws["busbw"]}


def run_benchmark(ops: List[str], max_log_size: int = 24,
                  min_log_size: int = 12, trials: int = 20) -> None:
    dist.init_distributed()
    topo = dist.get_topology()
    print(f"# comms benchmark: {topo.describe()}")
    for op in ops:
        print(f"\n## {op}")
        print(f"{'size':>12} {'latency(us)':>14} {'algbw(GB/s)':>12} "
              f"{'busbw(GB/s)':>12}")
        for p in range(min_log_size, max_log_size + 1, 2):
            r = time_collective(op, 1 << p, trials=trials)
            print(f"{r['size_bytes']:>12} {r['latency_us']:>14.1f} "
                  f"{r['algbw_gbps']:>12.2f} {r['busbw_gbps']:>12.2f}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ops", nargs="*", default=["all_reduce"],
                   choices=list(OPS) + ["all"])
    p.add_argument("--maxsize", type=int, default=24,
                   help="log2 of the largest message in bytes")
    p.add_argument("--minsize", type=int, default=12)
    p.add_argument("--trials", type=int, default=20)
    args = p.parse_args()
    ops = list(OPS) if "all" in args.ops else args.ops
    run_benchmark(ops, args.maxsize, args.minsize, args.trials)


if __name__ == "__main__":
    main()
