"""ZeRO++ quantized collectives (qwZ / qgZ).

TPU-native re-design of the reference's compressed collectives
(``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce``,
``:81 all_to_all_loco_quant_reduce``, backed by ``csrc/quantization/``
swizzled-quant CUDA kernels):

- :func:`quantized_all_gather` — **qwZ**: the int8 weight all-gather.
  Each member quantizes its shard group-wise (``ops/quantization.py``),
  the int8 payload + fp32 scales cross the wire (~4x fewer bytes than
  bf16, ~8x with ``num_bits=4`` whose nibbles are packed two-per-byte),
  and members dequantize locally.
- :func:`quantized_reduce_scatter` — **qgZ**: gradient reduce-scatter as
  quantize -> all-to-all -> local dequant-reduce.  With a multi-axis group
  (e.g. ``("data", "data_sub")``) the hops run hierarchically, innermost
  (node-local ICI) axis first with re-quantization between hops — the
  reference's 2-hop qgZ that keeps the DCN hop at 1/N of the bytes.

Both run hop-per-axis with mutually inverse hop orders, so
``quantized_all_gather(quantized_reduce_scatter(x, group=g), group=g)``
reconstructs the original layout for any axis tuple (the ZeRO++ wire
pattern).

Both are in-graph collectives: call them inside ``shard_map`` (or any
traced context with mesh axis names).  Dequantization math runs as plain
XLA elementwise ops (one multiply-add; the Pallas kernels matter for the
standalone quantize path, not here where fusion is free).

Quantization noise makes these LOSSY: the convergence-parity tests
(tests/unit/test_quantized_comm.py) pin the error bounds and show a
manual-DP training loop tracking its full-precision twin.
"""
from __future__ import annotations

import math
from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.comm.comm import _resolve_axes, comms_logger
from deepspeed_tpu.ops.quantization import quantize

GroupLike = Union[None, str, Sequence[str]]


def _axes_size(axes: Tuple[str, ...]) -> int:
    import deepspeed_tpu.comm as dist

    topo = dist.get_topology()
    return int(np.prod([topo.axis_size(a) for a in axes]))


def _chunk_group_size(chunk_numel: int, group_size: int,
                      num_bits: int = 8) -> int:
    """Largest quant-group size <= group_size that divides the chunk, so
    groups never straddle chunk boundaries.  Kept even so int4 nibble
    pairs never straddle a group."""
    gs = group_size if chunk_numel % group_size == 0 else \
        math.gcd(chunk_numel, group_size)
    while gs > 1 and (gs % 2 or chunk_numel % gs):
        gs -= 1
    if num_bits == 4 and gs % 2:
        raise ValueError(
            f"int4 packing needs an even group size but the shard has "
            f"{chunk_numel} elements (odd): pad the array or use num_bits=8")
    if gs < 16:
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            f"quantized collective: shard numel {chunk_numel} only admits "
            f"quant groups of {gs} elements — per-group fp32 scales now "
            "rival the payload and the 'compressed' transfer may exceed "
            "the uncompressed one; pad shards to a multiple of "
            f"{group_size} to restore the compression ratio")
    return max(gs, 1)


def _deq(vals: jax.Array, scale: jax.Array) -> jax.Array:
    # symmetric quantization on the wire: offset is identically zero and
    # never transferred (halves the fp32 side-channel bytes)
    return vals.astype(jnp.float32) * scale


def _pack4(v: jax.Array) -> jax.Array:
    """[G, gs] int8 holding int4-range values -> [G, gs//2] packed bytes."""
    pair = v.reshape(v.shape[0], -1, 2)
    lo = pair[..., 0] & jnp.int8(0x0F)
    hi = (pair[..., 1] & jnp.int8(0x0F)) << 4
    return lo | hi


def _unpack4(p: jax.Array) -> jax.Array:
    """Inverse of :func:`_pack4` (arithmetic shifts sign-extend)."""
    lo = (p << 4) >> 4
    hi = p >> 4
    return jnp.stack([lo, hi], axis=-1).reshape(p.shape[0], -1)


def _wire(v: jax.Array, num_bits: int) -> jax.Array:
    return _pack4(v) if num_bits == 4 else v


def _unwire(v: jax.Array, num_bits: int) -> jax.Array:
    return _unpack4(v) if num_bits == 4 else v


def quantized_all_gather(x: jax.Array, group: GroupLike = None,
                         axis: int = 0, num_bits: int = 8,
                         group_size: int = 2048) -> jax.Array:
    """qwZ: all-gather with an int8 (or packed-int4) payload on the wire.

    ``x`` is this member's shard; the result is the tiled gather along
    ``axis``.  For a SINGLE-axis group the layout matches
    ``comm.all_gather`` exactly.  Multi-axis groups gather hop-by-hop in
    the inverse order of :func:`quantized_reduce_scatter`'s hops, so
    RS -> AG round-trips to the original layout — but the standalone
    multi-axis layout is chunk-PERMUTED relative to
    ``comm.all_gather(group=(a, b))`` (the standard hierarchical-
    collective permutation); only pair it with its RS twin, or gather one
    axis at a time when layout-compatibility with the flat collective
    matters.  Lossy: ~0.4% relative error per group (int8 symmetric).
    """
    axes = _resolve_axes(group)
    out = x
    for ax in axes:                       # inverse of RS's reversed(axes)
        out = _quant_gather_hop(out, ax, axis, num_bits, group_size)
    return out


def _quant_gather_hop(x: jax.Array, ax: str, axis: int, num_bits: int,
                      group_size: int) -> jax.Array:
    n = _axes_size((ax,))
    if n == 1:
        return x
    numel = int(np.prod(x.shape))
    gs = _chunk_group_size(numel, group_size, num_bits)
    qt = quantize(x, num_bits=num_bits, group_size=gs)
    payload = _wire(qt.values, num_bits)
    comms_logger.append("quantized_all_gather",
                        int(payload.size + 4 * qt.scale.size) * n, n, None,
                        "qwZ")
    vals = lax.all_gather(payload, ax)         # int8 on the wire
    sc = lax.all_gather(qt.scale, ax)
    full = jax.vmap(lambda v, s: _deq(_unwire(v, num_bits), s))(vals, sc)
    full = full.reshape(n, -1)[:, :numel]
    full = full.reshape((n,) + tuple(x.shape)).astype(x.dtype)
    out = jnp.moveaxis(full, 0, axis)          # [..., n, d_axis, ...]
    shape = list(x.shape)
    shape[axis] *= n
    return out.reshape(shape)


def quantized_reduce_scatter(x: jax.Array, group: GroupLike = None,
                             op: str = "avg", num_bits: int = 8,
                             group_size: int = 2048) -> jax.Array:
    """qgZ: reduce-scatter (dim 0) as quantize -> all-to-all -> local
    dequant-reduce, hop per mesh axis, innermost axis first.

    Equivalent (up to quantization noise) to hierarchical
    ``lax.psum_scatter`` hops in the same order; each hop re-quantizes so
    every wire transfer is int8/packed-int4.  ``op``: "sum" or "avg" (avg
    divides by the total group size, the reference's gradient-averaging
    semantics).
    """
    assert op in ("sum", "avg")
    axes = _resolve_axes(group)
    out = x
    # innermost mesh axis (ICI-adjacent) first: the reference's
    # intra-node-then-inter-node 2-hop order
    for ax in reversed(axes):
        out = _quant_scatter_hop(out, ax, num_bits, group_size)
    if op == "avg":
        out = out / _axes_size(axes)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# LoCo: error-feedback quantized reduce-scatter (reference
# ``all_to_all_loco_quant_reduce``, coalesced_collectives.py:81)
# ---------------------------------------------------------------------------

def loco_error_init(x: jax.Array, group: GroupLike = None) -> Tuple:
    """Zero error-feedback buffers for :func:`loco_quantized_reduce_scatter`
    — one per hop (the reference keeps separate intra/inter-node error
    buffers for its 2-hop qgZ; shapes shrink by the hop's axis size)."""
    axes = _resolve_axes(group)
    errs = []
    shape = tuple(x.shape)
    for ax in reversed(axes):
        n = _axes_size((ax,))
        if n == 1:
            continue
        errs.append(jnp.zeros(shape, jnp.float32))
        shape = (shape[0] // n,) + shape[1:]
    return tuple(errs)


def loco_quantized_reduce_scatter(x: jax.Array, err: Tuple = None,
                                  group: GroupLike = None, op: str = "avg",
                                  num_bits: int = 8,
                                  group_size: int = 2048
                                  ) -> Tuple[jax.Array, Tuple]:
    """LoCo qgZ: quantized reduce-scatter with per-hop ERROR FEEDBACK —
    each hop adds the previous step's quantization residual before
    quantizing and carries the new residual forward, making the
    compression noise unbiased over steps (gradients no longer
    systematically lose what one step's rounding dropped).

    Returns ``(reduced, new_err)``; thread ``new_err`` into the next
    step's call.  ``err=None`` starts from zeros
    (:func:`loco_error_init`).  Same wire bytes as
    :func:`quantized_reduce_scatter` — compensation is local math.
    """
    assert op in ("sum", "avg")
    axes = _resolve_axes(group)
    hops = [ax for ax in reversed(axes) if _axes_size((ax,)) > 1]
    if err is None:
        err = loco_error_init(x, group)
    assert len(err) == len(hops), (
        f"LoCo error state has {len(err)} hop buffers, the group needs "
        f"{len(hops)} — pass err from the previous call (or None)")
    out = x
    new_errs = []
    for ax, e in zip(hops, err):
        out, e_new = _quant_scatter_hop(out, ax, num_bits, group_size,
                                        error=e)
        new_errs.append(e_new)
    if op == "avg":
        out = out / _axes_size(tuple(axes))
    return out.astype(x.dtype), tuple(new_errs)


def _quant_scatter_hop(x: jax.Array, ax: str, num_bits: int,
                       group_size: int, error: jax.Array = None):
    n = _axes_size((ax,))
    if n == 1:
        return x if error is None else (x, error)
    d0 = x.shape[0]
    assert d0 % n == 0, (
        f"reduce-scatter dim {d0} not divisible by axis {ax!r} size {n}")
    chunk_shape = (d0 // n,) + tuple(x.shape[1:])
    chunk_numel = int(np.prod(chunk_shape))
    gs = _chunk_group_size(chunk_numel, group_size, num_bits)
    if error is not None:                      # LoCo compensation
        x = x.astype(jnp.float32) + error
    qt = quantize(x, num_bits=num_bits, group_size=gs)
    payload = _wire(qt.values, num_bits)
    comms_logger.append("quantized_reduce_scatter",
                        int(payload.size + 4 * qt.scale.size), n, None,
                        "qgZ")
    gc = chunk_numel // gs                     # quant groups per chunk
    # rows are ordered chunk-major (groups never straddle chunks), so a
    # tiled dim-0 all-to-all routes chunk i's rows to member i
    vals = lax.all_to_all(payload, ax, split_axis=0, concat_axis=0,
                          tiled=True)
    sc = lax.all_to_all(qt.scale, ax, split_axis=0, concat_axis=0,
                        tiled=True)
    parts = _deq(_unwire(vals, num_bits), sc).reshape(n, gc * gs)
    out = jnp.sum(parts, axis=0).reshape(chunk_shape)
    if error is None:
        return out
    # residual of what THIS member actually put on the wire
    local_deq = _deq(qt.values, qt.scale).reshape(-1)[
        : int(np.prod(x.shape))].reshape(x.shape)
    return out, (x.astype(jnp.float32) - local_deq)
