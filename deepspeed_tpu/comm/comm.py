"""Communication facade.

TPU-native re-design of ``deepspeed/comm/comm.py`` (the torch.distributed-like
module API) on top of a single JAX/XLA backend.  Two calling modes share one
set of functions:

- **In-graph** (inside ``jit`` + ``shard_map`` with mesh axes bound): the
  functions lower straight to XLA collectives (``lax.psum``,
  ``lax.all_gather``, ``lax.psum_scatter``, ``lax.all_to_all``,
  ``lax.ppermute``) which ride ICI/DCN.  This replaces the reference's NCCL
  process-group calls; there is no capability probing because XLA always has
  fused collectives (SURVEY §2.4 "TPU equivalent").
- **Eager** (concrete arrays, no axis bound): the call is wrapped in a jitted
  ``shard_map`` over the current global mesh — used by tests and the comms
  benchmark (``ds_bench`` equivalent).  Eager inputs carry a leading
  per-shard dimension of the group size, mirroring "each rank contributes a
  local buffer".

Every op is recorded by the ``CommsLogger`` (op, message size, group size;
wall latency for eager ops), feeding ``log_summary`` — the reference's
``timed_op`` decorator (``comm/comm.py:101``) recreated where XLA semantics
allow.
"""
from __future__ import annotations

import os
import time
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map

from deepspeed_tpu.comm import watchdog as _watchdog
from deepspeed_tpu.comm.comms_logging import CommsLogger
from deepspeed_tpu.parallel.topology import MeshTopology, AXIS_ORDER
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.utils.logging import log_dist, logger

GroupLike = Union[None, str, Tuple[str, ...], Sequence[str]]

comms_logger = CommsLogger()


class _CommState:
    initialized: bool = False
    backend_name: Optional[str] = None
    topology: Optional[MeshTopology] = None


_state = _CommState()


# ---------------------------------------------------------------------------
# Bootstrap (reference: init_distributed comm.py:625 + launcher env plumbing)
# ---------------------------------------------------------------------------


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     init_method: Optional[str] = None,
                     rank: int = -1,
                     world_size: int = -1,
                     timeout=None,
                     dist_init_required: Optional[bool] = None) -> None:
    """Bootstrap multi-process JAX if a coordinator is configured.

    Single-process (one host, N local chips) needs no rendezvous — the
    single-controller runtime already sees every local device.  Multi-host
    runs set ``DSTPU_COORDINATOR`` (or the standard JAX env/cloud TPU
    metadata) and we call ``jax.distributed.initialize`` — the analogue of
    the reference's ``torch.distributed.init_process_group`` rendezvous.
    With ``auto_mpi_discovery`` (default), the Slurm / OpenMPI / PMI /
    torchrun / Cloud-TPU environment is consulted when no explicit
    coordinator is configured (reference ``mpi_discovery`` + managed-env
    patching, comm.py:694,754).
    """
    if _state.initialized:
        return
    coordinator = init_method or os.environ.get("DSTPU_COORDINATOR")
    num_processes = world_size if world_size > 0 else int(
        os.environ.get("DSTPU_NUM_PROCESSES", "0"))
    process_id = rank if rank >= 0 else int(os.environ.get("DSTPU_PROCESS_ID", "-1"))
    if not coordinator and auto_mpi_discovery:
        from deepspeed_tpu.launcher.env_discovery import \
            discover_distributed_env

        found = discover_distributed_env()
        if found and found.get("auto"):
            jax.distributed.initialize()
            log_dist("jax.distributed initialized from Cloud-TPU pod "
                     "metadata", ranks=[0])
            _state.backend_name = dist_backend
            _state.initialized = True
            return
        if found:
            coordinator = found["coordinator_address"]
            num_processes = found["num_processes"]
            process_id = found["process_id"]
            log_dist(
                f"distributed env discovered from {found['source']}: "
                f"rank={process_id}/{num_processes} "
                f"coordinator={coordinator}", ranks=[0])
    if coordinator and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id if process_id >= 0 else None,
        )
        log_dist(
            f"jax.distributed initialized: coordinator={coordinator} "
            f"processes={num_processes}", ranks=[0])
    _state.backend_name = dist_backend
    _state.initialized = True


def is_initialized() -> bool:
    return _state.initialized


def get_backend_name() -> Optional[str]:
    return _state.backend_name


def initialize_mesh(dp: Optional[int] = None, tp: int = 1, pp: int = 1,
                    sp: int = 1, ep: int = 1, hpz: int = 1,
                    devices: Optional[Sequence[jax.Device]] = None) -> MeshTopology:
    """Create and install the global mesh (reference
    ``initialize_mesh_device``, comm.py:609).  ``hpz`` splits the data axis
    for ZeRO++ hpZ secondary partitioning (``dp`` counts total data-parallel
    replicas, including the split)."""
    if not _state.initialized:
        init_distributed()
    topo = MeshTopology(dp=dp, tp=tp, pp=pp, sp=sp, ep=ep, hpz=hpz,
                        devices=devices)
    _state.topology = topo
    return topo


def set_topology(topology: MeshTopology) -> None:
    _state.topology = topology


def get_topology() -> MeshTopology:
    if _state.topology is None:
        initialize_mesh()
    return _state.topology


def peek_topology() -> Optional[MeshTopology]:
    """The installed topology, or None — never auto-installs a default mesh
    (unlike ``get_topology``)."""
    return _state.topology


def get_mesh() -> Mesh:
    return get_topology().mesh


def get_world_size(group: GroupLike = None) -> int:
    topo = get_topology()
    axes = _resolve_axes(group)
    return int(np.prod([topo.axis_size(a) for a in axes])) if axes else 1


def get_rank() -> int:
    """Host process index (single-controller: one python per host)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0


def get_process_count() -> int:
    return jax.process_count()


def barrier(group: GroupLike = None) -> None:
    """Barrier: flush local device work; on multi-host runs additionally
    synchronize every process (a psum over all global devices, the JAX
    analogue of ``torch.distributed.barrier``).

    Fault site ``comm.barrier`` (straggle delays this rank; drop skips
    the cross-process sync so peers stall); the cross-process sync runs
    under the collective watchdog when one is armed."""
    directive = faults.hook("comm.barrier")
    if directive is not None:
        dkind, dparam = directive
        if dkind == "straggle":
            time.sleep(dparam)
        elif dkind == "drop":
            logger.error("[fault-injection] comm.barrier: dropped on rank "
                         f"{jax.process_index()} — peers will stall")
            return
    jax.effects_barrier()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        t0 = time.perf_counter()
        _watchdog.guard(
            lambda: multihost_utils.sync_global_devices(
                "deepspeed_tpu.comm.barrier"),
            what="comm.barrier")
        comms_logger.append("barrier", 0, jax.process_count(),
                            time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# Group resolution
# ---------------------------------------------------------------------------


def _resolve_axes(group: GroupLike) -> Tuple[str, ...]:
    if group is None:
        topo = _state.topology
        if topo is None:
            return tuple(AXIS_ORDER)
        return tuple(a for a in AXIS_ORDER if topo.axis_size(a) > 1) or (AXIS_ORDER[1],)
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _nbytes(x) -> int:
    return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize


# ---------------------------------------------------------------------------
# Collectives
# ---------------------------------------------------------------------------

_REDUCE_OPS = {
    "sum": lax.psum,
    "avg": lambda x, axes: lax.pmean(x, axes),
    "mean": lambda x, axes: lax.pmean(x, axes),
    "max": lax.pmax,
    "min": lax.pmin,
}


def all_reduce(x, op: str = "sum", group: GroupLike = None, log_name: str = "all_reduce"):
    """Reduce across the group; result replicated on every member.

    In-graph: ``lax.psum``-family over the axis names.  Eager: ``x`` has a
    leading dim equal to the group size (one slice per member).
    """
    axes = _resolve_axes(group)
    if _is_traced(x):
        comms_logger.append("all_reduce", _nbytes(x), _axes_size(axes), None, log_name)
        return _REDUCE_OPS[op](x, axes)
    return _eager_collective("all_reduce", x, axes, op=op, log_name=log_name)


def inference_all_reduce(x, group: GroupLike = None):
    return all_reduce(x, "sum", group, log_name="inference_all_reduce")


def all_gather(x, group: GroupLike = None, axis: int = 0, tiled: bool = True,
               log_name: str = "all_gather"):
    """Gather shards along ``axis`` from every group member.

    In-graph result has the gathered (tiled) dimension ``group_size *
    x.shape[axis]`` — the reference's ``all_gather_into_tensor``.
    """
    axes = _resolve_axes(group)
    if _is_traced(x):
        comms_logger.append("all_gather_into_tensor", _nbytes(x) * _axes_size(axes),
                            _axes_size(axes), None, log_name)
        return lax.all_gather(x, axes, axis=axis, tiled=tiled)
    return _eager_collective("all_gather", x, axes, axis=axis, log_name=log_name)


def reduce_scatter(x, op: str = "sum", group: GroupLike = None, axis: int = 0,
                   log_name: str = "reduce_scatter"):
    """Reduce across the group and scatter shards along ``axis``
    (the reference's ``reduce_scatter_tensor``)."""
    axes = _resolve_axes(group)
    if _is_traced(x):
        comms_logger.append("reduce_scatter_tensor", _nbytes(x), _axes_size(axes),
                            None, log_name)
        out = lax.psum_scatter(x, axes, scatter_dimension=axis, tiled=True)
        if op in ("avg", "mean"):
            out = out / _axes_size(axes)
        return out
    return _eager_collective("reduce_scatter", x, axes, op=op, axis=axis,
                             log_name=log_name)


def all_to_all(x, group: GroupLike = None, split_axis: int = 0,
               concat_axis: int = 0, tiled: bool = True,
               log_name: str = "all_to_all"):
    """All-to-all over a single axis (the reference's
    ``all_to_all_single``, comm.py:337)."""
    axes = _resolve_axes(group)
    assert len(axes) == 1, "all_to_all requires a single mesh axis"
    if _is_traced(x):
        comms_logger.append("all_to_all_single", _nbytes(x), _axes_size(axes),
                            None, log_name)
        return lax.all_to_all(x, axes[0], split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)
    return _eager_collective("all_to_all", x, axes, split_axis=split_axis,
                             concat_axis=concat_axis, log_name=log_name)


def ppermute(x, perm, group: GroupLike = None, log_name: str = "ppermute"):
    """Point-to-point ring permute (the TPU-native replacement for the
    reference's send/recv pairs in ``runtime/pipe/p2p.py``)."""
    axes = _resolve_axes(group)
    assert len(axes) == 1, "ppermute requires a single mesh axis"
    if _is_traced(x):
        comms_logger.append("ppermute", _nbytes(x), _axes_size(axes), None, log_name)
        return lax.ppermute(x, axes[0], perm)
    return _eager_collective("ppermute", x, axes, perm=perm, log_name=log_name)


def broadcast(x, src: int = 0, group: GroupLike = None, log_name: str = "broadcast"):
    """Broadcast the ``src`` member's value to the whole group.

    In-graph lowering is a binomial-tree ``ppermute`` ladder: log2(n)
    rounds, ranks [0, step) forwarding to [step, 2*step) — (n-1) total
    buffer hops, the textbook broadcast wire cost (a masked psum would
    ride a full all-reduce ring, ~2x the bytes plus the adds)."""
    axes = _resolve_axes(group)
    assert len(axes) == 1, "broadcast requires a single mesh axis"
    if _is_traced(x):
        comms_logger.append("broadcast", _nbytes(x), _axes_size(axes), None, log_name)
        n = _axes_size(axes)
        if n == 1:
            return x
        idx = lax.axis_index(axes[0])
        rank = (idx - src) % n                     # src relabeled to rank 0
        val = x
        step = 1
        while step < n:
            perm = [((src + r) % n, (src + r + step) % n)
                    for r in range(step) if r + step < n]
            recv = lax.ppermute(val, axes[0], perm)
            is_receiver = (rank >= step) & (rank < min(2 * step, n))
            val = jnp.where(is_receiver, recv, val)
            step *= 2
        return val
    return _eager_collective("broadcast", x, axes, src=src, log_name=log_name)


def axis_index(group: GroupLike = None):
    axes = _resolve_axes(group)
    assert len(axes) == 1
    return lax.axis_index(axes[0])


def _axes_size(axes: Tuple[str, ...]) -> int:
    topo = _state.topology
    if topo is None:
        return 1
    return int(np.prod([topo.axis_size(a) for a in axes]))


# ---------------------------------------------------------------------------
# Eager path: shard_map over the global mesh + wall-clock timing
# ---------------------------------------------------------------------------


# Compiled eager-collective cache: rebuilding the jitted shard_map closure on
# every call would recompile each time and the logged "latency" would be
# compile time. Key on everything that changes the lowered program.
_EAGER_CACHE: dict = {}


def _corrupt_local_view(out, fraction: float):
    """Honor a ``("corrupt", fraction)`` directive: scale the first
    ``fraction`` of THIS process's addressable shards of the collective
    result — a lossy link delivering corrupted data to one receiver.
    The global array is rebuilt from local shards only (no cross-process
    traffic), so peers keep their clean copies: replication is broken
    exactly the way the desync detector must catch."""
    arrays = []
    for sh in out.addressable_shards:
        data = np.array(sh.data)                 # local host copy
        flat = data.reshape(-1)
        k = max(1, int(flat.size * fraction))
        flat[:k] = flat[:k] * 1024.0 + 1.0       # deterministic scale+shift
        arrays.append(jax.device_put(data, sh.device))
    return jax.make_array_from_single_device_arrays(out.shape, out.sharding,
                                                    arrays)


def _eager_collective(kind: str, x, axes: Tuple[str, ...], **kw):
    log_name = kw.pop("log_name", kind)
    # fault site (comm.all_reduce / comm.all_gather / comm.broadcast /
    # ...): one hook firing per EAGER call — in-graph collectives lower
    # to XLA and cannot be intercepted.  No injector active -> one
    # module-global None check, nothing else.
    directive = faults.hook(f"comm.{kind}")
    if directive is not None:
        dkind, dparam = directive
        if dkind == "straggle":
            # models a rank arriving late from slow compute: the sleep
            # happens OUTSIDE the timed bracket, so the straggler records
            # a short wait while every peer's timing absorbs the delay —
            # the inversion build_straggler_report keys on (argmin)
            logger.warning(f"[fault-injection] comm.{kind}: straggling "
                           f"{dparam:.3f}s on rank {jax.process_index()}")
            time.sleep(dparam)
        elif dkind == "drop":
            logger.error(f"[fault-injection] comm.{kind}: dropped on rank "
                         f"{jax.process_index()} — peers will stall in "
                         "the collective")
            return jnp.asarray(x)
    topo = get_topology()
    mesh = topo.mesh
    n = _axes_size(axes)
    x = jnp.asarray(x)
    assert x.shape[0] == n, (
        f"eager {kind}: leading dim {x.shape[0]} must equal group size {n} "
        f"(one slice per member)")
    spec_axes = axes[0] if len(axes) == 1 else tuple(axes)
    in_spec = P(spec_axes, *([None] * (x.ndim - 1)))

    perm_kw = kw.get("perm")
    cache_key = (id(mesh), kind, axes, x.shape, str(x.dtype),
                 kw.get("op"), kw.get("axis"), kw.get("split_axis"),
                 kw.get("concat_axis"), kw.get("src"),
                 tuple(perm_kw) if perm_kw is not None else None)
    cached = _EAGER_CACHE.get(cache_key)

    if kind == "all_reduce":
        op = kw["op"]

        def f(xs):
            r = _REDUCE_OPS[op](jnp.squeeze(xs, 0), axes)
            return r[None]
        out_spec = in_spec
    elif kind == "all_gather":
        def f(xs):
            return lax.all_gather(jnp.squeeze(xs, 0), axes, axis=0, tiled=True)[None]
        out_spec = in_spec
    elif kind == "reduce_scatter":
        op = kw["op"]

        def f(xs):
            r = lax.psum_scatter(jnp.squeeze(xs, 0), axes,
                                 scatter_dimension=0, tiled=True)
            if op in ("avg", "mean"):
                r = r / n
            return r[None]
        out_spec = in_spec
    elif kind == "all_to_all":
        sa, ca = kw["split_axis"], kw["concat_axis"]

        def f(xs):
            return lax.all_to_all(jnp.squeeze(xs, 0), axes[0], split_axis=sa,
                                  concat_axis=ca, tiled=True)[None]
        out_spec = in_spec
    elif kind == "ppermute":
        perm = kw["perm"]

        def f(xs):
            return lax.ppermute(jnp.squeeze(xs, 0), axes[0], perm)[None]
        out_spec = in_spec
    elif kind == "broadcast":
        src = kw["src"]

        def f(xs):
            local = jnp.squeeze(xs, 0)
            idx = lax.axis_index(axes[0])
            masked = jnp.where(idx == src, local, jnp.zeros_like(local))
            return lax.psum(masked, axes[0])[None]
        out_spec = in_spec
    else:  # pragma: no cover
        raise ValueError(kind)

    with mesh:
        if cached is None:
            fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(in_spec,),
                                   out_specs=out_spec))
            _EAGER_CACHE[cache_key] = fn
            warm_up = True
        else:
            fn = cached
            warm_up = False
        # the timed bracket INCLUDES the sharded device_put: on a
        # multi-process mesh it synchronizes with the peers' previous
        # collective retiring, so a straggling rank's delay surfaces
        # here on every peer (measured: the execute+block segment alone
        # reads ~ms even when the put stalled 400ms on a slow peer).
        # Guarded: a dropped/wedged peer hangs this path, not just the
        # execution wait.
        t0 = time.perf_counter()
        x_sharded = _watchdog.guard(
            lambda: jax.device_put(x, NamedSharding(mesh, in_spec)),
            what=f"comm.{kind} (device_put)")
        if warm_up:
            # first call pays trace+compile; exclude it from timing
            _watchdog.guard(lambda: jax.block_until_ready(fn(x_sharded)),
                            what=f"comm.{kind} (warm-up)")
            t0 = time.perf_counter()
        out = _watchdog.guard(lambda: jax.block_until_ready(fn(x_sharded)),
                              what=f"comm.{kind}")
        dt = time.perf_counter() - t0
    comms_logger.append(kind if kind != "all_gather" else "all_gather_into_tensor",
                        _nbytes(x) // max(n, 1) if kind == "all_reduce" else _nbytes(x),
                        n, dt, log_name)
    if directive is not None and directive[0] == "corrupt":
        logger.error(f"[fault-injection] comm.{kind}: corrupting "
                     f"{directive[1]:.2f} of the local result view on rank "
                     f"{jax.process_index()}")
        out = _corrupt_local_view(out, directive[1])
    return out


def straggler_report(min_spread_s: float = 0.020,
                     min_ratio: float = 2.0) -> dict:
    """Cross-rank per-op straggler aggregation: gather every process's
    mean eager-collective latencies and name the rank peers wait for
    (``resilience/distributed.py build_straggler_report``).  Costs one
    small allgather; single-process returns per-op stats with no
    straggler named (nothing to compare)."""
    from deepspeed_tpu.resilience.distributed import (allgather_json,
                                                      build_straggler_report)

    local = comms_logger.per_op_mean_latency()
    per_rank = allgather_json(local)
    return build_straggler_report(per_rank, min_spread_s=min_spread_s,
                                  min_ratio=min_ratio)


def log_summary(show_straggler: bool = False) -> str:
    """Print the comms table (reference ``comm.py:428``).

    ``show_straggler`` additionally prints per-call straggler effect
    (max-vs-avg latency) and, on multi-process runs, the CROSS-RANK
    straggler report naming the rank every collective waits for."""
    out = comms_logger.log_summary(show_straggler=show_straggler)
    if show_straggler and jax.process_count() > 1:
        section = comms_logger.render_straggler_report(straggler_report())
        logger.info("\n" + section)
        out = out + "\n" + section
    return out


def configure(comms_config=None) -> None:
    if comms_config is not None:
        comms_logger.configure(comms_config)
