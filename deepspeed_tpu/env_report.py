"""Environment report CLI (the reference's ``ds_report``,
``deepspeed/env_report.py``): versions, visible devices, and feature
availability on this host."""
from __future__ import annotations

import importlib
import sys

GREEN_OK = "[OKAY]"
RED_NO = "[NO]"


def _try_version(mod_name: str) -> str:
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return ""


def feature_report() -> list:
    """(name, available, detail) rows for TPU-relevant features."""
    rows = []
    try:
        import jax

        devs = jax.devices()
        rows.append(("jax devices", True, f"{len(devs)} x {devs[0].platform}"))
        try:
            kind = devs[0].device_kind
            rows.append(("device kind", True, kind))
        except Exception:
            pass
        try:
            from jax.experimental import pallas  # noqa: F401

            rows.append(("pallas", True, "importable"))
        except Exception:
            rows.append(("pallas", False, ""))
    except Exception as e:  # pragma: no cover
        rows.append(("jax devices", False, str(e)))
    return rows


def main() -> int:
    print("-" * 60)
    print("DeepSpeed-TPU environment report")
    print("-" * 60)
    print(f"python version ............ {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy", "pydantic"):
        v = _try_version(mod)
        status = GREEN_OK if v else RED_NO
        print(f"{mod:<26} {status} {v}")
    try:
        import deepspeed_tpu

        print(f"{'deepspeed_tpu':<26} {GREEN_OK} {deepspeed_tpu.__version__}")
    except Exception:
        print(f"{'deepspeed_tpu':<26} {RED_NO}")
    print("-" * 60)
    for name, ok, detail in feature_report():
        print(f"{name:<26} {GREEN_OK if ok else RED_NO} {detail}")
    print("-" * 60)
    return 0


if __name__ == "__main__":
    sys.exit(main())
