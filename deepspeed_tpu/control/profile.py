"""Per-host serving profiles: the offline sweep and its persistence.

The offline ``--autotune`` mode reuses the existing ``autotuning/``
ExperimentScheduler machinery (:func:`autotune_serving` wraps
:func:`~deepspeed_tpu.autotuning.scheduler.tune_space`) to search the
serving knob space, then persists the winner as a JSON profile keyed
by a **host fingerprint** — core count, accelerator device kind, NVMe
present — so the online controller on the same class of host starts
from a known-good point instead of the shipped defaults.  A profile
from a *different* fingerprint is rejected at load time: knob optima
do not transfer across host shapes (the 1-core dev container's optimum
is nothing like an 8-core NVMe bench host's).

Profile format (one JSON object)::

    {
      "version": 1,
      "fingerprint": {"cores": 8, "device": "cpu", "nvme": true},
      "knobs": {"engine.harvest_interval": 4, "engine.async_depth": 2},
      "metric": 1234.5,          # the sweep's objective at the winner
      "metric_name": "tok_per_s",
      "source": "sweep",
      "created": 1754300000.0
    }
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence

__all__ = ["HostProfile", "host_fingerprint", "fingerprint_key",
           "save_profile", "load_profile", "autotune_serving"]

PROFILE_VERSION = 1


def _has_nvme() -> bool:
    try:
        return any(e.startswith("nvme")
                   for e in os.listdir("/sys/class/nvme"))
    except OSError:
        return False


def host_fingerprint() -> Dict[str, Any]:
    """The profile key: what actually moves serving knob optima."""
    device = "cpu"
    try:
        import jax
        device = str(jax.devices()[0].device_kind)
    except Exception:
        pass
    return {"cores": int(os.cpu_count() or 1),
            "device": device.lower().replace(" ", "-"),
            "nvme": _has_nvme()}


def fingerprint_key(fp: Optional[Dict[str, Any]] = None) -> str:
    fp = fp or host_fingerprint()
    return (f"{fp['cores']}c_{fp['device']}_"
            f"{'nvme' if fp['nvme'] else 'nonvme'}")


@dataclass
class HostProfile:
    knobs: Dict[str, Any]
    fingerprint: Dict[str, Any] = field(default_factory=host_fingerprint)
    metric: Optional[float] = None
    metric_name: str = ""
    source: str = "sweep"
    created: float = 0.0
    version: int = PROFILE_VERSION

    @property
    def key(self) -> str:
        return fingerprint_key(self.fingerprint)


def _default_dir() -> str:
    return os.environ.get(
        "DSTPU_PROFILE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu"))


def _profile_path(path: Optional[str],
                  fp: Optional[Dict[str, Any]] = None) -> str:
    """A file path stays a file path; a directory (or None — the
    default cache dir) resolves to the fingerprint-keyed file name."""
    if path is not None and not os.path.isdir(path) \
            and path.endswith(".json"):
        return path
    base = path if path is not None else _default_dir()
    return os.path.join(base,
                        f"control_profile_{fingerprint_key(fp)}.json")


def save_profile(profile: HostProfile,
                 path: Optional[str] = None) -> str:
    """Write the profile; returns the resolved path."""
    if not profile.created:
        profile.created = time.time()
    out = _profile_path(path, profile.fingerprint)
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(asdict(profile), f, indent=2, sort_keys=True)
    os.replace(tmp, out)
    return out


def load_profile(path: Optional[str] = None, *,
                 fingerprint: Optional[Dict[str, Any]] = None,
                 strict: bool = True) -> Optional[HostProfile]:
    """Load the profile for this host (or ``fingerprint``); ``None``
    when absent, unreadable, or — with ``strict`` — keyed to a
    different host shape."""
    fp = fingerprint or host_fingerprint()
    target = _profile_path(path, fp)
    try:
        with open(target) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "knobs" not in doc:
        return None
    prof = HostProfile(
        knobs=dict(doc.get("knobs") or {}),
        fingerprint=dict(doc.get("fingerprint") or {}),
        metric=doc.get("metric"),
        metric_name=str(doc.get("metric_name") or ""),
        source=str(doc.get("source") or ""),
        created=float(doc.get("created") or 0.0),
        version=int(doc.get("version") or 0))
    if strict and prof.fingerprint != fp:
        return None
    return prof


def autotune_serving(runner: Callable[[Dict[str, Any]], float],
                     space: Dict[str, Sequence], *,
                     tuner: str = "gridsearch",
                     metric_name: str = "tok_per_s",
                     n_trials: int = 1000,
                     early_stopping: Optional[int] = None,
                     exps_dir: Optional[str] = None,
                     seed: int = 0,
                     save_to: Optional[str] = None
                     ) -> Optional[HostProfile]:
    """Offline knob sweep on the autotuning substrate.

    ``runner(point)`` measures one knob assignment (``point`` maps knob
    name → candidate value) and returns the metric (higher is better);
    exceptions quarantine that point, exactly like a crashed training
    experiment.  Returns the winning :class:`HostProfile` (saved to
    ``save_to`` — a file, a directory, or the default cache dir when
    ``""`` — if requested), or ``None`` when every point failed.
    """
    from deepspeed_tpu.autotuning.scheduler import tune_space

    best = tune_space(
        {}, dict(space),
        lambda cfg: runner(dict(cfg.get("_tuning_point") or {})),
        tuner=tuner, n_trials=n_trials, early_stopping=early_stopping,
        exps_dir=exps_dir, seed=seed)
    if best is None or best.metric_val is None:
        return None
    prof = HostProfile(
        knobs=dict(best.ds_config.get("_tuning_point") or {}),
        metric=float(best.metric_val), metric_name=metric_name,
        source=f"sweep:{tuner}", created=time.time())
    if save_to is not None:
        save_profile(prof, save_to or None)
    return prof
