"""The online controller: reads the signal plane, drives the knobs.

One :class:`Controller` runs on the serving/training host loop (the
engine ticks it every ``interval`` steps — no thread of its own, so
arming it changes nothing structurally when it never decides).  Each
tick it

1. reads a signal snapshot (a plain ``{name: float}`` dict from an
   injectable feed — :func:`engine_signal_feed` composes one from
   ``host_stats`` deltas, pool pressure, tiering counters, pipeline
   ``submit_wait`` and SLO burn rates),
2. runs the **rule layer**: hard signal→knob reactions (prefetch on
   under spill pressure, earlier router deferral under SLO burn) with
   per-rule cooldowns,
3. runs the **hill-climb layer**: one in-flight *trial* at a time —
   step one knob, let the system settle ``settle`` ticks, then judge
   the objective against the trial's baseline with hysteresis:
   clear improvement → accept and keep climbing; clear regression →
   revert and flip direction; neither → quiet revert.  Repeated
   regressions on one knob within ``guard_window`` ticks trip the
   **oscillation guard**: the knob is frozen for ``freeze`` ticks
   (the revert-on-regression + frozen-knob penalty window).

Every decision is emitted as a ``cat="control"`` trace event plus
``dstpu_control_*`` metrics series, so ``trace_summarize --control``
can reconstruct the full decision log from any chrome/flight export,
and every knob change names the signal that motivated it.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.control.knobs import KnobRegistry
from deepspeed_tpu.telemetry import trace
from deepspeed_tpu.telemetry.metrics import metrics as _metrics

__all__ = ["Controller", "Rule", "engine_signal_feed", "prefetch_rule",
           "slo_shed_rule"]


@dataclass
class Rule:
    """Hard signal→knob reaction, evaluated every tick before the
    hill-climb.  ``predicate(signal_value)`` true and the knob not at
    ``value`` → apply it, attributed to ``signal``."""

    knob: str
    signal: str
    predicate: Callable[[float], bool]
    value: Any
    cooldown: int = 8
    name: str = ""

    def __post_init__(self) -> None:
        if not self.name:
            self.name = f"{self.signal}->{self.knob}"


def prefetch_rule(knob: str = "kv.prefetch",
                  signal: str = "tiering_spill_rate",
                  threshold: float = 0.5) -> Rule:
    """Spill pressure with prefetch off: restores kick back to the
    critical path — turn read-ahead on."""
    return Rule(knob=knob, signal=signal,
                predicate=lambda v: v >= threshold, value=True)


def slo_shed_rule(knob: str = "router.burn_defer",
                  signal: str = "slo_burn_max",
                  threshold: float = 1.5, defer_at: float = 1.0) -> Rule:
    """SLO error budget burning: lower the router's deferral threshold
    so low-priority load queues instead of competing — shedding rides
    the router's existing admission hooks from there."""
    return Rule(knob=knob, signal=signal,
                predicate=lambda v: v >= threshold, value=defer_at)


class Controller:
    """Rule + hill-climb knob policy with hysteresis and an
    oscillation guard.  Deterministic given its signal feed and clock
    (both injectable — the unit-test contract)."""

    def __init__(self, knobs: KnobRegistry,
                 signals: Callable[[], Dict[str, float]],
                 objective: str = "throughput", *,
                 clock: Callable[[], float] = time.monotonic,
                 settle: int = 2, hysteresis: float = 0.05,
                 cooldown: int = 4, guard_window: int = 16,
                 guard_reverts: int = 2, freeze: int = 32,
                 smooth: float = 1.0,
                 rules: Optional[List[Rule]] = None,
                 name: str = "control") -> None:
        if objective.startswith("-"):
            self._obj_key, self._obj_sign = objective[1:], -1.0
        else:
            self._obj_key, self._obj_sign = objective, 1.0
        self.knobs = knobs
        self.name = name
        self._signals = signals
        self._clock = clock
        self._settle = max(1, int(settle))
        self._hysteresis = float(hysteresis)
        self._cooldown = max(0, int(cooldown))
        self._guard_window = max(1, int(guard_window))
        self._guard_reverts = max(1, int(guard_reverts))
        self._freeze = max(1, int(freeze))
        self._smooth = min(1.0, max(0.0, float(smooth)))
        self._rules = list(rules or [])
        self._tick = 0
        self._obj: Optional[float] = None
        self._trial: Optional[Dict[str, Any]] = None
        self._rr = 0                             # round-robin cursor
        # per-knob policy state
        self._kstate: Dict[str, Dict[str, Any]] = {}
        self._rule_until: Dict[str, int] = {}
        self.decision_log: List[Dict[str, Any]] = []
        self.counts: Dict[str, int] = {
            "ticks": 0, "decisions": 0, "probes": 0, "accepts": 0,
            "reverts": 0, "settles": 0, "rules": 0, "freezes": 0,
            "unfreezes": 0, "guard_violations": 0}

    # -- state helpers ---------------------------------------------------

    def _ks(self, name: str) -> Dict[str, Any]:
        st = self._kstate.get(name)
        if st is None:
            st = {"dir": 1, "cooldown_until": 0, "frozen_until": 0,
                  "reverts": deque()}
            self._kstate[name] = st
        return st

    def _blocked(self, name: str) -> bool:
        st = self._ks(name)
        return (self._tick < st["frozen_until"]
                or self._tick < st["cooldown_until"])

    def frozen(self) -> List[str]:
        return [n for n, st in self._kstate.items()
                if self._tick < st["frozen_until"]]

    # -- the tick --------------------------------------------------------

    def tick(self) -> List[Dict[str, Any]]:
        """One control evaluation; returns the decisions it made (also
        appended to ``decision_log`` and emitted to trace/metrics)."""
        t0 = time.perf_counter()
        self._tick += 1
        self.counts["ticks"] += 1
        sig = dict(self._signals() or {})
        raw = sig.get(self._obj_key)
        if raw is not None:
            v = self._obj_sign * float(raw)
            self._obj = (v if self._obj is None else
                         self._smooth * v
                         + (1.0 - self._smooth) * self._obj)
        decisions: List[Dict[str, Any]] = []
        self._expire_freezes(decisions)
        self._run_rules(sig, decisions)
        if self._trial is not None:
            self._judge_trial(decisions)
        elif self._obj is not None:
            self._start_trial(decisions)
        self._emit(decisions, t0)
        return decisions

    # -- layers ----------------------------------------------------------

    def _expire_freezes(self, decisions: List[Dict[str, Any]]) -> None:
        for kname, st in self._kstate.items():
            if st["frozen_until"] and self._tick >= st["frozen_until"]:
                st["frozen_until"] = 0
                st["reverts"].clear()
                val = self.knobs.value(kname)
                decisions.append(self._decision(
                    "unfreeze", kname, val, val, signal="guard"))

    def _run_rules(self, sig: Dict[str, float],
                   decisions: List[Dict[str, Any]]) -> None:
        for rule in self._rules:
            if rule.knob not in self.knobs or rule.signal not in sig:
                continue
            if self._tick < self._rule_until.get(rule.name, 0):
                continue
            st = self._ks(rule.knob)
            if self._tick < st["frozen_until"]:
                continue
            if not rule.predicate(float(sig[rule.signal])):
                continue
            old, new = self.knobs.set(rule.knob, rule.value)
            if new == old:
                continue
            # a rule override aborts any trial probing the same knob
            if self._trial is not None and \
                    self._trial["knob"] == rule.knob:
                self._trial = None
            self._rule_until[rule.name] = self._tick + rule.cooldown
            decisions.append(self._decision(
                "rule", rule.knob, old, new, signal=rule.signal))

    def _start_trial(self, decisions: List[Dict[str, Any]]) -> None:
        candidates = [k for k in self.knobs.tunable()
                      if k.kind != "bool"]
        if not candidates:
            return
        for off in range(len(candidates)):
            knob = candidates[(self._rr + off) % len(candidates)]
            if self._blocked(knob.name):
                continue
            st = self._ks(knob.name)
            cur = knob.get()
            new = knob.clamp(cur + st["dir"] * knob.step)
            if new == cur:                    # at a bound: turn around
                st["dir"] = -st["dir"]
                new = knob.clamp(cur + st["dir"] * knob.step)
                if new == cur:
                    continue                  # degenerate range
            self._rr = (self._rr + off + 1) % len(candidates)
            self.knobs.set(knob.name, new)
            self._trial = {"knob": knob.name, "old": cur, "new": new,
                           "baseline": self._obj,
                           "start": self._tick}
            decisions.append(self._decision(
                "probe", knob.name, cur, new, signal=self._obj_key))
            return

    def _judge_trial(self, decisions: List[Dict[str, Any]]) -> None:
        trial = self._trial
        if self._tick - trial["start"] < self._settle:
            return
        self._trial = None
        kname = trial["knob"]
        knob = self.knobs.get(kname)
        st = self._ks(kname)
        base = trial["baseline"]
        obj = self._obj
        gain = ((obj - base) / max(abs(base), 1e-9)
                if (obj is not None and base is not None) else 0.0)
        if gain >= self._hysteresis:
            # clearly better: keep it and keep climbing this direction
            # (no cooldown — momentum while improving)
            decisions.append(self._decision(
                "accept", kname, trial["old"], trial["new"],
                signal=self._obj_key, gain=round(gain, 4)))
            return
        # not clearly better: put the old value back
        self.knobs.set(kname, trial["old"])
        cool = self._cooldown + knob.cooldown
        st["cooldown_until"] = self._tick + cool
        if gain <= -self._hysteresis:
            # clear regression: oscillation-guard bookkeeping
            st["dir"] = -st["dir"]
            st["reverts"].append(self._tick)
            while (st["reverts"] and
                   st["reverts"][0] <= self._tick - self._guard_window):
                st["reverts"].popleft()
            decisions.append(self._decision(
                "revert", kname, trial["new"], trial["old"],
                signal=self._obj_key, gain=round(gain, 4)))
            if len(st["reverts"]) >= self._guard_reverts:
                st["frozen_until"] = self._tick + self._freeze
                st["reverts"].clear()
                val = self.knobs.value(kname)
                decisions.append(self._decision(
                    "freeze", kname, val, val, signal="guard",
                    until=st["frozen_until"]))
        else:
            # neutral: quiet revert, try the other direction later
            st["dir"] = -st["dir"]
            decisions.append(self._decision(
                "settle", kname, trial["new"], trial["old"],
                signal=self._obj_key, gain=round(gain, 4)))

    # -- emission --------------------------------------------------------

    _COUNT_KEY = {"probe": "probes", "accept": "accepts",
                  "revert": "reverts", "settle": "settles",
                  "rule": "rules", "freeze": "freezes",
                  "unfreeze": "unfreezes"}

    def _decision(self, action: str, knob: str, old: Any, new: Any,
                  *, signal: str, **extra: Any) -> Dict[str, Any]:
        d = {"tick": self._tick, "action": action, "knob": knob,
             "old": old, "new": new, "signal": signal,
             "objective": (round(self._obj, 6)
                           if self._obj is not None else None)}
        d.update(extra)
        return d

    def _emit(self, decisions: List[Dict[str, Any]],
              t0: float) -> None:
        for d in decisions:
            self.decision_log.append(d)
            self.counts["decisions"] += 1
            key = self._COUNT_KEY.get(d["action"])
            if key:
                self.counts[key] += 1
            if trace.enabled:
                trace.event("control_decision", cat="control", **d)
            if _metrics.enabled:
                _metrics.counter(
                    "dstpu_control_decisions_total",
                    "Control-plane decisions by knob and action",
                    labels=("knob", "action")).labels(
                        knob=d["knob"], action=d["action"]).inc()
                if d["new"] is not None and d["action"] != "probe" \
                        and not isinstance(d["new"], bool):
                    _metrics.gauge(
                        "dstpu_control_knob",
                        "Current control-plane knob values",
                        labels=("knob",)).labels(
                            knob=d["knob"]).set(float(d["new"]))
        if trace.enabled and decisions:
            trace.add_complete("control_tick", t0,
                               time.perf_counter() - t0, cat="control",
                               tick=self._tick,
                               decisions=len(decisions))
        if _metrics.enabled:
            _metrics.counter("dstpu_control_ticks_total",
                             "Control-plane evaluation ticks").inc()

    # -- reporting -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {**self.counts,
                "objective": (round(self._obj, 6)
                              if self._obj is not None else None),
                "frozen": self.frozen(),
                "knobs": self.knobs.snapshot()}


def engine_signal_feed(engine,
                       clock: Callable[[], float] = time.monotonic
                       ) -> Callable[[], Dict[str, float]]:
    """Compose the ragged engine's signal plane into one flat snapshot
    per tick: ``host_stats`` counter *rates* over the inter-tick
    window (throughput = decode ticks/s — the objective), per-dispatch
    efficiency ratios, KV pool pressure, tiering spill/restore rates,
    pipeline ``submit_wait`` share, and the max SLO burn rate."""
    state: Dict[str, Any] = {}

    def _delta(key: str, cur: float) -> float:
        prev = state.get(key, 0.0)
        state[key] = cur
        return cur - prev

    def read() -> Dict[str, float]:
        now = clock()
        st = engine.host_stats
        out: Dict[str, float] = {}
        dt = now - state.get("t", now)
        state["t"] = now
        dticks = _delta("ticks", st.ticks)
        ddisp = _delta("dispatches", st.dispatches)
        dgets = _delta("blocking_gets", st.blocking_gets)
        dwait = _delta("submit_wait",
                       engine._pipe_timers.seconds.get("submit_wait",
                                                       0.0))
        if dt > 0:
            out["throughput"] = dticks / dt
            out["dispatch_rate"] = ddisp / dt
            out["submit_wait_frac"] = min(1.0, dwait / dt)
        out["blocking_gets_per_dispatch"] = dgets / max(ddisp, 1)
        alloc = getattr(engine, "allocator", None)
        if alloc is not None:
            # the engine's own pressure definition: in-use fraction
            # plus the queued-request overload term
            usable = max(engine.num_pages - 1, 1)
            in_use = usable - alloc.free_pages
            out["pool_pressure"] = (in_use / usable
                                    + len(engine.waiting))
        tiering = getattr(engine, "tiering", None)
        if tiering is not None:
            tc = tiering.counters
            dspills = _delta("spills", float(tc.get("spills", 0)))
            drestores = _delta("restores", float(tc.get("restores", 0)))
            dfall = _delta("spill_fallbacks",
                           float(tc.get("spill_fallbacks", 0)))
            if dt > 0:
                out["tiering_spill_rate"] = dspills / dt
                out["tiering_restore_rate"] = drestores / dt
                out["tiering_fallback_rate"] = dfall / dt
        slo = getattr(engine, "slo", None)
        if slo is not None:
            try:
                burns = [float(v.get("burn_rate") or 0.0)
                         for v in slo.evaluate().values()]
                out["slo_burn_max"] = max(burns) if burns else 0.0
            except Exception:
                pass
        return out

    return read
