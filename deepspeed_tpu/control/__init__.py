"""Closed-loop control plane: the signal plane drives the knobs.

The system's perf-critical knobs (speculation mode/k, harvest
interval, async depth, KV-tiering prefetch and budgets, AIO buffer
count, decode block, router admission thresholds) were hand-tuned per
host; PRs 10 and 13 built the signal plane (tracer spans, histogram
quantiles, SLO burn rates, per-stage counters) that makes tuning them
*observable*.  This package closes the loop:

- :mod:`~deepspeed_tpu.control.knobs` — the typed knob surface
  (:class:`KnobRegistry`): bounds, step, cooldown, apply callbacks
  wired into the ragged engine, tiered KV store, router, and moment
  stream; recompile-triggering knobs are fenced offline-only.
- :mod:`~deepspeed_tpu.control.controller` — the online
  :class:`Controller`: rule + hill-climb policy with hysteresis and an
  oscillation guard, every decision a ``cat="control"`` trace event
  plus ``dstpu_control_*`` metrics.
- :mod:`~deepspeed_tpu.control.profile` — the offline ``--autotune``
  sweep (on the ``autotuning/`` ExperimentScheduler substrate) and the
  per-host profile that seeds the online starting point.

``DSTPU_CONTROL=0`` is the kill switch: :func:`control_enabled` gates
every attach point, so the armed system degrades to the structurally
pre-control one.
"""
from __future__ import annotations

import os

from deepspeed_tpu.control.controller import (Controller, Rule,
                                              engine_signal_feed,
                                              prefetch_rule,
                                              slo_shed_rule)
from deepspeed_tpu.control.knobs import (Knob, KnobRegistry, router_knobs,
                                         swapper_knobs)
from deepspeed_tpu.control.profile import (HostProfile, autotune_serving,
                                           fingerprint_key,
                                           host_fingerprint, load_profile,
                                           save_profile)

__all__ = ["Controller", "Rule", "Knob", "KnobRegistry", "HostProfile",
           "autotune_serving", "control_enabled", "engine_signal_feed",
           "fingerprint_key", "host_fingerprint", "load_profile",
           "prefetch_rule", "router_knobs", "save_profile",
           "slo_shed_rule", "swapper_knobs"]


def control_enabled() -> bool:
    """The ``DSTPU_CONTROL=0`` kill switch (default: enabled — but the
    controller still only runs where config/kwargs arm it)."""
    return os.environ.get("DSTPU_CONTROL", "1") != "0"
