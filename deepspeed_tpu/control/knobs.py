"""Typed knob surface for the closed-loop control plane.

Every perf-critical runtime parameter the system grew — the serving
pipeline's ``harvest_interval``/``async_depth``, the tiered KV store's
prefetch toggle and window depths, the router's burn-rate admission
thresholds, the moment stream's ``buffer_count`` — is declared here as
a :class:`Knob`: bounds, step, kind, an extra per-knob cooldown, and an
``apply`` callback wired into the owning subsystem.  The online
:class:`~deepspeed_tpu.control.controller.Controller` only ever touches
knobs through a :class:`KnobRegistry`, which clamps and types every
write, so a policy bug can propose garbage and the subsystem still
receives an in-bounds value of the right type.

Knobs whose value is baked into a compiled program (``decode_block``,
speculation ``k``/mode) carry ``recompiles=True``: they are excluded
from the online tunable set (``tunable()``) — changing them mid-run
would trigger fresh XLA compilations on the hot path, breaking the
engine's zero-recompile steady-state contract — and are reachable only
by the offline ``--autotune`` sweep / profile seeding, which run before
warmup where a compile is paid once and amortized.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Knob", "KnobRegistry", "router_knobs", "swapper_knobs"]


@dataclass
class Knob:
    """One runtime parameter the control plane may drive.

    ``get``/``apply`` close over the owning object; ``apply`` must be
    safe at the call points the owner exposes it from (the registry
    never defers — a deferred-apply knob hides the latency inside its
    own callback, as the swapper's ``set_buffer_count`` does).
    """

    name: str
    get: Callable[[], Any]
    apply: Callable[[Any], None]
    lo: float = 0.0
    hi: float = 1.0
    step: float = 1.0
    kind: str = "int"            # "int" | "float" | "bool"
    cooldown: int = 0            # extra settle ticks after a change
    recompiles: bool = False     # baked into a compiled program
    doc: str = ""

    def clamp(self, value: Any) -> Any:
        if self.kind == "bool":
            return bool(value)
        v = min(max(float(value), float(self.lo)), float(self.hi))
        return int(round(v)) if self.kind == "int" else v


class KnobRegistry:
    """Ordered, typed collection of knobs — the controller's only
    write path into the system.  ``set`` clamps to the declared bounds
    and refuses recompile-triggering knobs unless the caller explicitly
    opts in (profile seeding at construction time, before warmup)."""

    def __init__(self) -> None:
        self._knobs: "OrderedDict[str, Knob]" = OrderedDict()

    def register(self, knob: Knob) -> Knob:
        if knob.name in self._knobs:
            raise ValueError(f"knob {knob.name!r} already registered")
        self._knobs[knob.name] = knob
        return knob

    def merge(self, other: "KnobRegistry") -> "KnobRegistry":
        """Fold another registry's knobs in (e.g. router + engine knobs
        under one controller)."""
        for k in other._knobs.values():
            self.register(k)
        return self

    # -- introspection ---------------------------------------------------

    def __contains__(self, name: str) -> bool:
        return name in self._knobs

    def __len__(self) -> int:
        return len(self._knobs)

    def names(self) -> List[str]:
        return list(self._knobs)

    def get(self, name: str) -> Knob:
        return self._knobs[name]

    def value(self, name: str) -> Any:
        return self._knobs[name].get()

    def tunable(self) -> List[Knob]:
        """The online-safe set: everything that does NOT force a
        recompile when changed mid-run."""
        return [k for k in self._knobs.values() if not k.recompiles]

    def snapshot(self) -> Dict[str, Any]:
        return {name: k.get() for name, k in self._knobs.items()}

    # -- the write path --------------------------------------------------

    def set(self, name: str, value: Any, *,
            allow_recompile: bool = False) -> tuple:
        """Clamp, type, and apply; returns ``(old, new)``.  The apply
        callback runs even when ``new == old`` is False — idempotent
        re-applies are the callbacks' problem, and every one here is."""
        knob = self._knobs[name]
        if knob.recompiles and not allow_recompile:
            raise RuntimeError(
                f"knob {name!r} recompiles the hot path; online policy "
                "must not touch it (offline sweep / profile seed only)")
        old = knob.get()
        new = knob.clamp(value)
        if new != old:
            knob.apply(new)
        return old, new

    def apply_profile(self, knobs: Dict[str, Any], *,
                      allow_recompile: bool = True) -> Dict[str, Any]:
        """Seed knob values from a per-host profile (unknown names are
        skipped — profiles outlive code).  Returns what was applied.
        Runs at construction time, so recompiling knobs are fair game
        by default."""
        applied: Dict[str, Any] = {}
        for name, value in (knobs or {}).items():
            if name not in self._knobs:
                continue
            knob = self._knobs[name]
            if knob.recompiles and not allow_recompile:
                continue
            _, new = self.set(name, value,
                              allow_recompile=allow_recompile)
            applied[name] = new
        return applied


# -- knob builders for the non-engine owners ------------------------------
# (the engine builds its own in ``RaggedInferenceEngineV2.knob_registry``
# — these exist so the router and the moment-stream swapper expose the
# same typed surface, mergeable under one controller)

def router_knobs(router, prefix: str = "router.") -> KnobRegistry:
    """The scale-out router's admission thresholds: SLO-burn deferral
    and shedding multipliers plus the per-replica queue cap — all plain
    host attributes the dispatch path reads fresh, so runtime writes
    are trivially safe."""
    reg = KnobRegistry()
    reg.register(Knob(
        f"{prefix}burn_defer", lambda: router.burn_defer,
        lambda v: setattr(router, "burn_defer", float(v)),
        lo=0.25, hi=4.0, step=0.25, kind="float",
        doc="burn rate above which low-priority work defers"))
    reg.register(Knob(
        f"{prefix}burn_shed", lambda: router.burn_shed,
        lambda v: setattr(router, "burn_shed", float(v)),
        lo=0.5, hi=8.0, step=0.5, kind="float",
        doc="burn rate above which low-priority work sheds"))
    reg.register(Knob(
        f"{prefix}queue_cap", lambda: router.queue_cap,
        lambda v: setattr(router, "queue_cap", max(int(v), 1)),
        lo=1, hi=max(4 * int(router.queue_cap), 8), step=1, kind="int",
        doc="per-replica admission queue cap"))
    if hasattr(router, "set_prefill_fraction"):
        # disaggregated serving: the controller adapts the
        # prefill:decode replica ratio to the live prompt-length mix
        # (set_prefill_fraction re-derives the role map, keeping >= 1
        # replica per role; a no-op in fused mode) and bounds how many
        # handoff export rounds may be in flight per prefill replica
        reg.register(Knob(
            f"{prefix}prefill_fraction",
            lambda: router.prefill_fraction,
            router.set_prefill_fraction,
            lo=0.1, hi=0.9, step=0.1, kind="float",
            doc="share of role-split replicas carrying the prefill "
                "role"))
        reg.register(Knob(
            f"{prefix}handoff_depth", lambda: router.handoff_depth,
            lambda v: setattr(router, "handoff_depth", max(int(v), 1)),
            lo=1, hi=8, step=1, kind="int",
            doc="in-flight prefill->decode handoff export rounds per "
                "prefill replica"))
    return reg


def swapper_knobs(swapper, prefix: str = "swap.") -> KnobRegistry:
    """The moment-stream swapper's IO-window sizing.  ``buffer_count``
    applies through :meth:`set_buffer_count`, which defers the resize
    to the next read-quiescent point — the knob is runtime-safe by the
    swapper's own contract, not by luck."""
    reg = KnobRegistry()
    reg.register(Knob(
        f"{prefix}buffer_count", lambda: swapper.buffer_count,
        swapper.set_buffer_count,
        lo=1, hi=8, step=1, kind="int",
        doc="pinned staging buffers / read-ahead+write-back depth"))
    return reg
