"""MoQ: Mixture-of-Quantization training quantizer.

Re-design of the reference ``runtime/quantize.py:14 Quantizer`` (the MoQ
engine): weights quantize progressively during training — bit-width
halves from ``q_start_bits`` toward ``q_target_bits`` every
``q_period[layer]`` steps, the quantized value blends with the
full-precision value by a decaying ratio (``q_mixed_fp16``), and when
Hessian eigenvalue ratios are supplied (``runtime/eigenvalue.py``),
sharper layers stretch their periods — ``period * (1 + floor(ev * 4))``
— so they keep precision longer.

Functional: ``quantize_params(params, step)`` returns a new tree; the
actual rounding reuses the STE quantizers in ``compression/utils.py``
(sym/asym/binary/ternary), so gradients pass straight through when used
inside the loss for QAT.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression.utils import (asym_quantize, binary_quantize,
                                             sym_quantize, ternary_quantize)
from deepspeed_tpu.utils.logging import log_dist


class Quantizer:
    """Reference constructor surface; ``layer_paths`` names the param
    subtrees treated as layers (defaults to every 2-D+ leaf's parent)."""

    def __init__(self, q_groups: int = 1, q_mixed_fp16: bool = False,
                 q_change_ratio: float = 0.01, q_type: str = "symmetric",
                 q_rounding: str = "nearest", q_verbose: bool = False,
                 q_eigenvalue: bool = False,
                 use_quantizer_kernel: bool = False,
                 q_start_bits: int = 16, q_target_bits: int = 8,
                 q_period: int = 1000, layer_num: int = 0):
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type
        self.q_rounding = q_rounding
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.use_quantizer_kernel = use_quantizer_kernel
        self.q_start_bits = q_start_bits
        self.q_target_bits = q_target_bits
        self.q_period = q_period
        self.layer_num = layer_num
        self.qsteps = 0
        self.quantize_real_ratio = 1.0

    # -- schedule -------------------------------------------------------

    def step(self) -> None:
        self.qsteps += 1

    def update_fp16_ratio(self) -> None:
        """Mixed-precision blend decays toward pure-quantized (reference
        ``update_fp16_ratio``)."""
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)

    def bits_at(self, step: int, eigenvalue_ratio: Optional[float] = None
                ) -> int:
        """Current bit-width: halves every (possibly eigenvalue-
        stretched) period until the target."""
        period = self.q_period
        if eigenvalue_ratio is not None:
            period = period * (1 + math.floor(eigenvalue_ratio * 4))
        bits = self.q_start_bits
        halvings = step // max(period, 1)
        for _ in range(halvings):
            if bits <= self.q_target_bits:
                break
            bits = max(bits // 2, self.q_target_bits)
        return bits

    # -- quantization ---------------------------------------------------

    def _fake_quant(self, w: jax.Array, bits: int) -> jax.Array:
        groups = min(self.q_groups, max(w.size, 1))
        if bits == 1:
            return binary_quantize(w, groups)
        if bits == 2:
            return ternary_quantize(w, groups)
        fn = asym_quantize if self.q_type == "asymmetric" else sym_quantize
        return fn(w, bits, groups)

    def compute_quantization(self, w: jax.Array, index: int = 0,
                             factor: float = 1.0,
                             eigenvalue_ratio: Optional[float] = None
                             ) -> jax.Array:
        bits = self.bits_at(self.qsteps, eigenvalue_ratio)
        if bits >= 16:
            return w                       # not yet in the schedule
        wq = self._fake_quant(w.astype(jnp.float32), bits).astype(w.dtype)
        if self.q_mixed_fp16 and bits >= self.q_target_bits - 1:
            wq = (w * self.quantize_real_ratio +
                  (1.0 - self.quantize_real_ratio) * wq)
        return wq

    def quantize_params(self, params: Any, overflow: bool = False,
                        eigenvalue_ratios: Optional[Dict[str, float]]
                        = None) -> Any:
        """One MoQ tick over a param tree (reference ``quantize``):
        advances the step, decays the blend ratio, fake-quantizes every
        2-D+ floating leaf.  ``eigenvalue_ratios``: {path-substring:
        normalized eigenvalue} stretching that layer's period."""
        if overflow and not self.q_eigenvalue:
            return params
        self.step()
        self.update_fp16_ratio()
        import jax.tree_util as jtu

        flat, treedef = jtu.tree_flatten_with_path(params)
        out = []
        for kp, leaf in flat:
            path = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in kp)
            if (getattr(leaf, "ndim", 0) < 2 or
                    not jnp.issubdtype(leaf.dtype, jnp.floating)):
                out.append(leaf)
                continue
            ev = None
            if eigenvalue_ratios:
                for frag, val in eigenvalue_ratios.items():
                    if frag in path:
                        ev = val
                        break
            out.append(self.compute_quantization(
                leaf, eigenvalue_ratio=ev))
        if self.q_verbose:
            log_dist(f"MoQ step {self.qsteps}: bits="
                     f"{self.bits_at(self.qsteps)} "
                     f"ratio={self.quantize_real_ratio:.3f}", ranks=[0])
        return jtu.tree_unflatten(treedef, out)
