"""Functional train state.

The reference engine mutates module params, optimizer buffers, and loss-scale
counters in place; on TPU all of it is one immutable pytree threaded through
the jitted step (donated each call, so memory is reused in place by XLA).
"""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import struct

from deepspeed_tpu.runtime.precision import LossScaleState


@struct.dataclass
class TrainState:
    step: jnp.ndarray           # i32 global step counter
    params: Any                 # master params (fp32 when mixed precision)
    opt_state: Any
    scale: LossScaleState
    rng: jnp.ndarray            # PRNGKey for dropout etc.
    skipped_steps: jnp.ndarray  # i32, overflow-skipped step count
