"""Progressive Layer Dropping (arXiv:2010.13369).

Re-design of the reference ``runtime/progressive_layer_drop.py:10
ProgressiveLayerDrop`` + the layer-side gates its paper model uses: the
keep probability decays from 1.0 toward ``theta`` as
``(1 - theta) * exp(-gamma * step) + theta``, and layer i of L keeps
tokens with probability ``1 - (i/L) * (1 - theta_t)`` (deeper layers
drop more).  The host-side schedule is identical math; the TPU-side gate
is a flax wrapper using stochastic depth on scan-stacked blocks:
dropping a layer multiplies its residual branch by 0 (with 1/p rescale
on keep), so compiled shapes never change — the dropped layer's compute
is dead code the scheduler skips paying memory bandwidth for.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


class ProgressiveLayerDrop:
    """Host-side theta schedule (reference API parity)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True,
                "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = ((1.0 - self.theta) *
                              np.exp(-self.gamma * global_step) +
                              self.theta)
        return self.current_theta


def layer_keep_probs(theta_t: float, n_layers: int) -> np.ndarray:
    """Per-layer keep probability: layer i keeps with
    ``1 - i/L * (1 - theta_t)`` (paper's depth-linear schedule)."""
    i = np.arange(n_layers, dtype=np.float32)
    return 1.0 - (i / max(n_layers, 1)) * (1.0 - float(theta_t))


class PLDBlock(nn.Module):
    """Stochastic-depth wrapper: ``out = x + gate * block(x)`` where the
    gate is Bernoulli(keep_p) / keep_p during training and 1 at eval —
    the TPU-native realization of PLD's layer skip (static shapes; XLA
    dead-codes the dropped branch's memory traffic)."""

    block: Any
    keep_prob: float = 1.0

    @nn.compact
    def __call__(self, x, *args, keep_prob=None,
                 deterministic: bool = False):
        """``keep_prob`` may be passed per call as a TRACED value (the
        theta schedule changes every step — baking it into the module
        attribute would recompile the train step per step)."""
        out = self.block(x, *args)
        p = self.keep_prob if keep_prob is None else keep_prob
        if deterministic or (keep_prob is None and self.keep_prob >= 1.0):
            return out
        p = jnp.asarray(p, jnp.float32)
        rng = self.make_rng("pld")
        keep = jax.random.bernoulli(rng, p)
        # residual-style: dropping the layer returns the input unchanged,
        # keeping rescales so the expectation matches eval
        scale = jnp.where(keep, 1.0 / jnp.maximum(p, 1e-6),
                          0.0).astype(x.dtype)
        return x + (out - x) * scale
