"""muP (Maximal Update Parametrization) optimizer scaling.

Re-creation of the reference's muP optimizer integration
(``runtime/engine.py:1479``: ``MuAdam/MuAdamW/MuSGD`` from the ``mup``
package, Tensor Programs V, Yang & Hu et al.).  The mup package stores an
``infshape`` on every torch parameter via ``set_base_shapes``; here the
same information arrives as a ``base_shapes`` pytree (the shapes of the
proxy base model's params) and the per-leaf learning-rate multipliers
become an optax transform the engine chains after the base optimizer —
the scalar schedule lr stays outside the jit, multipliers live inside.

Scaling rules (TP-V Table 8; dims that differ from the base shape are
the "infinite" width dims):

==============  =====================  =====================
leaf kind       Adam lr mult           SGD lr mult
==============  =====================  =====================
no inf dims     1                      1
vector-like     1                      width_mult
(1 inf dim)     (1/fan_in_mult if      (fan_out side) /
                the inf dim is the     1/fan_in_mult (fan_in
                fan_in — output        side — output
                weights)               weights)
matrix-like     1 / fan_in_mult        fan_out_mult /
(2 inf dims)                           fan_in_mult
==============  =====================  =====================

Kernels follow the flax convention ``(..., fan_in, fan_out)``;
embeddings ``(vocab, embd)`` are input-weight-like (their lookup is a
selection, not a matmul over width) — the mup package classifies them
the same way.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class MupScaleState(NamedTuple):
    mults: Any            # params-shaped tree of f32 scalars


def _leaf_mult(shape, base_shape, rule: str, path: str) -> float:
    assert len(shape) == len(base_shape), (
        f"muP base shape rank mismatch at {path}: {shape} vs {base_shape}")
    ratios = [s / b for s, b in zip(shape, base_shape)]
    inf = [i for i, (s, b) in enumerate(zip(shape, base_shape)) if s != b]
    if not inf:
        return 1.0
    if len(shape) == 1:
        # biases / norm scales: vector-like, width_mult = its ratio
        return ratios[inf[0]] if rule == "sgd" else 1.0
    fan_in_dim, fan_out_dim = len(shape) - 2, len(shape) - 1
    fan_in_inf = fan_in_dim in inf
    fan_out_inf = fan_out_dim in inf
    if len(inf) >= 2 and fan_in_inf and fan_out_inf:    # hidden weights
        return (ratios[fan_out_dim] / ratios[fan_in_dim] if rule == "sgd"
                else 1.0 / ratios[fan_in_dim])
    if fan_in_inf:                                      # output weights
        return 1.0 / ratios[fan_in_dim]
    if fan_out_inf:                                     # input weights
        return ratios[fan_out_dim] if rule == "sgd" else 1.0
    # a leading (e.g. scan-layer or expert) dim changed: layer count is
    # not a width axis — no scaling
    return 1.0


def mup_multipliers(params: Any, base_shapes: Any, rule: str) -> Any:
    """Params-shaped tree of per-leaf lr multipliers.

    ``base_shapes``: same tree structure with shape tuples (or arrays —
    their ``.shape`` is used) from the BASE (narrow proxy) model.
    """
    assert rule in ("adam", "sgd"), rule

    def walk(path, leaf, base):
        b = tuple(getattr(base, "shape", base))
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        return jnp.float32(_leaf_mult(tuple(leaf.shape), b, rule, name))

    return jax.tree_util.tree_map_with_path(walk, params, base_shapes)


def scale_by_mup(base_shapes: Any,
                 rule: str = "adam") -> optax.GradientTransformation:
    """Chain element applying the muP per-leaf lr multipliers to the
    update direction (reference MuAdam/MuSGD mutate per-group lr; here
    lr is a host-side scalar, so the multiplier folds into the update)."""

    def init(params):
        return MupScaleState(mults=mup_multipliers(params, base_shapes,
                                                   rule))

    def update(updates, state, params=None):
        del params
        new = jax.tree_util.tree_map(
            lambda u, m: u * m.astype(u.dtype), updates, state.mults)
        return new, state

    return optax.GradientTransformation(init, update)
