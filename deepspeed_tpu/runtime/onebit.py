"""1-bit optimizers: OnebitAdam, ZeroOneAdam, OnebitLamb.

TPU-native re-design of the reference compressed-communication optimizer
family (``runtime/fp16/onebit/adam.py:14 OnebitAdam``,
``zoadam.py ZeroOneAdam``, ``lamb.py OnebitLamb``; wire backend
``runtime/comm/nccl.py:51``).  The algorithms (1-bit Adam,
arXiv:2102.02888; 0/1 Adam, arXiv:2202.06009; 1-bit LAMB,
arXiv:2104.06069) share one structure:

- **warmup** (``count < freeze_step``): exact Adam/LAMB with full-precision
  gradient averaging — Adam's variance needs honest second moments;
- **compression stage**: the variance is FROZEN; each member folds its
  LOCAL gradient into its momentum and the *momentum* is averaged through
  the 1-bit error-feedback all-reduce (``comm/compressed.py``) — 32x less
  wire traffic, and the only traffic there is.

These are optax-style ``GradientTransformation``s over LOCAL gradients:
run them inside ``shard_map`` with the data axes in scope (the engine does
this for ``optimizer.type: OneBitAdam`` at ZeRO stage 0; the reference has
the same stage-0 restriction).  With ``group=None`` (single member) the
comm degenerates to identity and the math reduces to Adam-with-frozen-
variance — useful for parity tests.

The error-feedback accumulators live in the optimizer state like any
moment: checkpointed, resumable, donated.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from deepspeed_tpu.comm.compressed import (compressed_allreduce,
                                           error_shapes)

GroupLike = Union[None, str, Sequence[str]]


class OnebitState(NamedTuple):
    count: jax.Array                 # int32 step counter
    mu: optax.Updates                # first moment
    nu: optax.Updates                # second moment (frozen after warmup)
    worker_error: jax.Array          # flat [padded] error feedback
    server_error: jax.Array          # flat [padded / n] server error


def _group_size(group: GroupLike) -> int:
    if group is None:
        return 1
    from deepspeed_tpu.comm.comm import _resolve_axes

    import deepspeed_tpu.comm as dist

    topo = dist.get_topology()
    return int(np.prod([topo.axis_size(a)
                        for a in _resolve_axes(group)]))


def _mean_over(group: GroupLike, x):
    if group is None:
        return x
    from deepspeed_tpu.comm.comm import _resolve_axes

    axes = _resolve_axes(group)
    return jax.tree_util.tree_map(lambda g: lax.pmean(g, axes), x)


def _zeros_errors(params, group: GroupLike):
    """Single flat error pair for the whole tree: the compressed sync runs
    over ONE concatenated buffer (the reference fuses the param group into
    one contiguous compressed all-reduce the same way — per-leaf
    collectives would drown small leaves in padding + latency)."""
    n = _group_size(group)
    total = sum(int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(params))
    we, se = error_shapes(total, n)
    return jnp.zeros((we,), jnp.float32), jnp.zeros((se,), jnp.float32)


def _compressed_sync(mu, we, se, group: GroupLike):
    """Momentum all-reduce through the 1-bit wire: one fused flat buffer
    for the whole tree."""
    if group is None or _group_size(group) == 1:
        return mu, we, se
    leaves, treedef = jax.tree_util.tree_flatten(mu)
    flat = jnp.concatenate([l.reshape(-1) for l in leaves])
    out, nwe, nse = compressed_allreduce(flat, we, se, group=group)
    splits = np.cumsum([int(np.prod(l.shape)) for l in leaves])[:-1]
    parts = jnp.split(out, splits)
    out_leaves = [p.reshape(l.shape) for p, l in zip(parts, leaves)]
    return jax.tree_util.tree_unflatten(treedef, out_leaves), nwe, nse


def scale_by_onebit_adam(b1: float = 0.9, b2: float = 0.999,
                         eps: float = 1e-8, freeze_step: int = 100000,
                         weight_decay: float = 0.0,
                         group: GroupLike = None
                         ) -> optax.GradientTransformation:
    """1-bit Adam update direction (lr applied by the caller).

    Matches reference ``OnebitAdam.step`` semantics: exact Adam during
    warmup with full-precision gradient averaging; after ``freeze_step``
    the variance freezes and only 1-bit-compressed momentum crosses the
    wire.  Bias correction uses the warmup-boundary convention of the
    paper (correction continues from the frozen step's count).
    ``weight_decay`` is decoupled (AdamW-style), added to the update
    direction — it is local math and never rides the wire.
    """

    def init(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        we, se = _zeros_errors(params, group)
        return OnebitState(jnp.zeros((), jnp.int32), mu, nu, we, se)

    def update(grads, state, params=None):
        count = state.count + 1
        frozen = count > freeze_step

        def warm(_):
            g = _mean_over(group, grads)
            mu = jax.tree_util.tree_map(
                lambda m, gg: b1 * m + (1 - b1) * gg, state.mu, g)
            nu = jax.tree_util.tree_map(
                lambda v, gg: b2 * v + (1 - b2) * gg * gg, state.nu, g)
            return mu, nu, state.worker_error, state.server_error

        def compressed(_):
            mu_local = jax.tree_util.tree_map(
                lambda m, gg: b1 * m + (1 - b1) * gg, state.mu, grads)
            mu_sync, we, se = _compressed_sync(
                mu_local, state.worker_error, state.server_error, group)
            return mu_sync, state.nu, we, se

        mu, nu, we, se = lax.cond(frozen, compressed, warm, operand=None)
        c = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** c
        # variance bias correction freezes with the variance
        cv = jnp.minimum(c, jnp.float32(freeze_step))
        bc2 = 1.0 - b2 ** cv
        updates = jax.tree_util.tree_map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu, nu)
        if weight_decay:
            assert params is not None, "weight_decay needs params"
            updates = jax.tree_util.tree_map(
                lambda u, p: u + weight_decay * p.astype(jnp.float32),
                updates, params)
        return updates, OnebitState(count, mu, nu, we, se)

    return optax.GradientTransformation(init, update)


def scale_by_zero_one_adam(b1: float = 0.9, b2: float = 0.999,
                           eps: float = 1e-8,
                           var_freeze_step: int = 100000,
                           var_update_scaler: int = 16,
                           local_step_scaler: int = 32678,
                           local_step_clipper: int = 16,
                           weight_decay: float = 0.0,
                           group: GroupLike = None
                           ) -> optax.GradientTransformation:
    """0/1 Adam (reference ``zoadam.py ZeroOneAdam``): linearly less
    frequent variance updates until ``var_freeze_step`` (every
    ``var_update_scaler`` steps), and compressed momentum sync only at
    exponentially spaced local steps afterwards (interval doubling,
    clipped at ``2**local_step_clipper``) — between sync points members
    run pure local steps, the '0-bit' part of 0/1 Adam.  The doubling
    resets every ``local_step_scaler`` steps (the reference couples the
    reset to learning-rate regime changes; with the lr schedule living
    outside the transform here, a step-count reset approximates it —
    documented divergence).
    """

    class ZoState(NamedTuple):
        count: jax.Array
        mu: optax.Updates
        nu: optax.Updates
        worker_error: optax.Updates
        server_error: optax.Updates
        next_sync: jax.Array         # step of the next momentum sync
        sync_interval: jax.Array     # current local-step interval

    def init(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        we, se = _zeros_errors(params, group)
        return ZoState(jnp.zeros((), jnp.int32), mu, nu, we, se,
                       jnp.asarray(var_freeze_step + 1, jnp.int32),
                       jnp.ones((), jnp.int32))

    def update(grads, state, params=None):
        count = state.count + 1
        in_warmup = count <= var_freeze_step

        def warm(_):
            g = _mean_over(group, grads)
            mu = jax.tree_util.tree_map(
                lambda m, gg: b1 * m + (1 - b1) * gg, state.mu, g)
            # variance updates thin out linearly: every var_update_scaler
            # steps (the reference's variance update interval policy)
            upd_var = (count % var_update_scaler == 0) | (count <= 1)
            nu = jax.tree_util.tree_map(
                lambda v, gg: jnp.where(upd_var,
                                        b2 * v + (1 - b2) * gg * gg, v),
                state.nu, g)
            return (mu, nu, state.worker_error, state.server_error,
                    state.next_sync, state.sync_interval)

        def local(_):
            mu_local = jax.tree_util.tree_map(
                lambda m, gg: b1 * m + (1 - b1) * gg, state.mu, grads)
            do_sync = count >= state.next_sync

            def sync(_):
                mu_s, we, se = _compressed_sync(
                    mu_local, state.worker_error, state.server_error,
                    group)
                # interval doubles, clipped; doubling restarts each
                # local_step_scaler window (lr-regime reset approximation)
                reset = (count % local_step_scaler) == 0
                interval = jnp.where(
                    reset, jnp.ones((), jnp.int32),
                    jnp.minimum(state.sync_interval * 2,
                                jnp.asarray(2 ** local_step_clipper,
                                            jnp.int32)))
                return mu_s, we, se, count + interval, interval

            def skip(_):
                return (mu_local, state.worker_error, state.server_error,
                        state.next_sync, state.sync_interval)

            mu, we, se, nxt, itv = lax.cond(do_sync, sync, skip,
                                            operand=None)
            return mu, state.nu, we, se, nxt, itv

        mu, nu, we, se, nxt, itv = lax.cond(in_warmup, warm, local,
                                            operand=None)
        c = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** c
        cv = jnp.minimum(c, jnp.float32(var_freeze_step))
        bc2 = 1.0 - b2 ** cv
        updates = jax.tree_util.tree_map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu, nu)
        if weight_decay:
            assert params is not None, "weight_decay needs params"
            updates = jax.tree_util.tree_map(
                lambda u, p: u + weight_decay * p.astype(jnp.float32),
                updates, params)
        return updates, ZoState(count, mu, nu, we, se, nxt, itv)

    return optax.GradientTransformation(init, update)


def scale_by_onebit_lamb(b1: float = 0.9, b2: float = 0.999,
                         eps: float = 1e-6, freeze_step: int = 100000,
                         min_trust: float = 0.01, max_trust: float = 10.0,
                         weight_decay: float = 0.0,
                         group: GroupLike = None
                         ) -> optax.GradientTransformation:
    """1-bit LAMB (reference ``onebit/lamb.py``): LAMB during warmup;
    after the freeze both the variance AND the per-layer trust ratios'
    denominator statistics freeze, and momentum syncs through the 1-bit
    wire.  The layerwise trust ratio ||p|| / ||update|| is recomputed
    from live params each step (it is local math, no comm)."""
    base = scale_by_onebit_adam(b1=b1, b2=b2, eps=eps,
                                freeze_step=freeze_step,
                                weight_decay=weight_decay, group=group)

    def init(params):
        return base.init(params)

    def update(grads, state, params=None):
        assert params is not None, "1-bit LAMB needs params for trust ratio"
        updates, new_state = base.update(grads, state, params)

        def trust(p, u):
            pn = jnp.linalg.norm(p.astype(jnp.float32))
            un = jnp.linalg.norm(u.astype(jnp.float32))
            ratio = jnp.where(
                (pn > 0) & (un > 0),
                jnp.clip(pn / jnp.maximum(un, 1e-12), min_trust, max_trust),
                1.0)
            return u * ratio

        return jax.tree_util.tree_map(trust, params, updates), new_state

    return optax.GradientTransformation(init, update)
