"""TiledLinear: split one big linear into a grid of tile kernels.

Re-design of the reference ``runtime/zero/tiling.py TiledLinear``: huge
projection matrices (embedding outputs, wide MLPs) become
``in_splits x out_splits`` independent kernels so no single parameter
exceeds the partition/offload granularity — under ZeRO-3 each tile
shards and streams independently, bounding peak gather size.  On TPU the
same trick also bounds the largest single all-gather when parameters are
offloaded to host memory.

``y[:, o] = sum_i x[:, i] @ W[i][o]`` — bitwise-equivalent (up to sum
order) to the untiled matmul, verified by test.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class TiledLinear(nn.Module):
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        assert in_dim % self.in_splits == 0, (
            f"input dim {in_dim} not divisible by in_splits "
            f"{self.in_splits}")
        assert self.features % self.out_splits == 0, (
            f"features {self.features} not divisible by out_splits "
            f"{self.out_splits}")
        din = in_dim // self.in_splits
        dout = self.features // self.out_splits
        xs = jnp.split(x, self.in_splits, axis=-1)
        outs = []
        for o in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                w = self.param(f"tile_{i}_{o}", self.kernel_init,
                               (din, dout), self.dtype)
                part = xs[i] @ w
                acc = part if acc is None else acc + part
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros,
                           (self.features,), self.dtype)
            y = y + b
        return y
