"""Hessian eigenvalue estimation by power iteration (MoQ support).

Re-design of the reference ``runtime/eigenvalue.py:13 Eigenvalue``: the
top Hessian eigenvalue per layer drives the Mixture-of-Quantization
precision schedule (sharper layers keep more bits).  The reference power-
iterates with ``torch.autograd.grad(create_graph=True)`` Hessian-vector
products; in JAX an HVP is one composition —
``jax.jvp(jax.grad(loss), (params,), (v,))`` — fully jittable, no graph
retention bookkeeping.

``eigenvalue(loss_fn, params, rng)`` -> {layer_path: eigenvalue} over
the requested top-level param groups, normalized to [0, 1] by the max
like the reference's post-processing.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def _inner(xs, ys) -> jax.Array:
    return sum(jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32))
               for x, y in zip(jax.tree_util.tree_leaves(xs),
                               jax.tree_util.tree_leaves(ys)))


def _normalize(v, stability: float):
    norm = jnp.sqrt(_inner(v, v)) + stability
    return jax.tree_util.tree_map(
        lambda x: jnp.nan_to_num(x / norm, nan=0.0, posinf=0.0,
                                 neginf=0.0), v)


class Eigenvalue:
    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num
        log_dist(f"enabled eigenvalue: max_iter={max_iter} tol={tol} "
                 f"layer_name={layer_name!r}", ranks=[0])

    def compute_eigenvalue(self, loss_fn: Callable, params: Any,
                           rng: Optional[jax.Array] = None,
                           sub_paths: Optional[list] = None
                           ) -> Dict[str, float]:
        """Top Hessian eigenvalue per selected param subtree.

        ``loss_fn(params) -> scalar``; ``sub_paths``: top-level keys to
        treat as layers (default: ``layer_name`` children, else every
        top-level key).  Returns eigenvalues normalized by their max
        (reference ``post_process``: ratios drive the MoQ schedule).
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        grad_fn = jax.grad(loss_fn)

        @jax.jit
        def hvp(p, v):
            return jax.jvp(grad_fn, (p,), (v,))[1]

        root = params
        if self.layer_name:
            for part in self.layer_name.split("/"):
                root = root[part]
        keys = sub_paths if sub_paths is not None else list(root)
        if self.layer_num:
            keys = keys[:self.layer_num]

        raw: Dict[str, float] = {}
        for key in keys:
            rng, sub = jax.random.split(rng)
            v = jax.tree_util.tree_map(
                lambda p, k=sub: jax.random.normal(
                    jax.random.fold_in(k, hash(p.shape) % (2 ** 31)),
                    p.shape, jnp.float32), root[key])
            v = _normalize(v, self.stability)
            ev = 0.0
            for it in range(self.max_iter):
                # HVP restricted to the subtree: zero tangents elsewhere
                tangent = jax.tree_util.tree_map(jnp.zeros_like, params)
                tangent = _set_subtree(tangent, self.layer_name, key, v)
                hv_full = hvp(params, tangent)
                hv = _get_subtree(hv_full, self.layer_name, key)
                new_ev = float(_inner(v, hv))
                v = _normalize(hv, self.stability)
                if it > 0 and abs(new_ev - ev) <= self.tol * max(
                        abs(ev), 1e-12):
                    ev = new_ev
                    break
                ev = new_ev
            raw[str(key)] = abs(ev)
            if self.verbose:
                log_dist(f"eigenvalue[{key}] = {ev:.4e}", ranks=[0])
        mx = max(raw.values()) or 1.0
        return {k: val / mx for k, val in raw.items()}


def _set_subtree(tree, layer_name: str, key, value):
    node = tree
    parents = []
    for part in [p for p in layer_name.split("/") if p]:
        parents.append((node, part))
        node = node[part]
    new = dict(node)
    new[key] = value
    for parent, part in reversed(parents):
        parent = dict(parent)
        parent[part] = new
        new = parent
    return new


def _get_subtree(tree, layer_name: str, key):
    node = tree
    for part in [p for p in layer_name.split("/") if p]:
        node = node[part]
    return node[key]
