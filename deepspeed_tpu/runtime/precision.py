"""Mixed precision: dynamic loss scaling + master-weight policy.

Functional re-design of the reference's ``runtime/fp16/loss_scaler.py``
(``LossScaler:67``, ``DynamicLossScaler:91``) and the master-weight schemes
of ``FP16_Optimizer`` / ``BF16_Optimizer``: instead of optimizer wrapper
classes with hooks, the scale and its hysteresis counters are plain fields
of the train state, updated inside the jitted step with ``jnp.where`` (no
data-dependent host control flow — XLA-friendly).

On TPU bf16 is the native compute dtype and needs no loss scaling; fp16
support is kept for parity and for fp16-native checkpoints.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    """Carried in TrainState; all fields are device scalars."""

    loss_scale: jnp.ndarray      # f32
    good_steps: jnp.ndarray      # i32 consecutive non-overflow steps
    hysteresis: jnp.ndarray      # i32 remaining tolerated overflows


def init_loss_scale(cfg) -> LossScaleState:
    """Build from an ``FP16Config`` (static scale when ``loss_scale`` > 0)."""
    if cfg.enabled and cfg.loss_scale == 0:
        scale = float(2.0 ** cfg.initial_scale_power)
    elif cfg.enabled:
        scale = float(cfg.loss_scale)
    else:
        scale = 1.0
    return LossScaleState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(cfg.hysteresis if cfg.enabled else 1, jnp.int32),
    )


def update_loss_scale(state: LossScaleState, overflow: jnp.ndarray,
                      dynamic: bool, loss_scale_window: int = 1000,
                      min_loss_scale: float = 1.0,
                      consecutive_hysteresis: bool = False,
                      init_hysteresis: int = 2) -> LossScaleState:
    """One scale update (reference ``DynamicLossScaler.update_scale``).

    Overflow: consume hysteresis; once exhausted halve the scale (bounded by
    ``min_loss_scale``).  ``loss_scale_window`` good steps: double the scale
    and optionally refill hysteresis.
    """
    if not dynamic:
        return state
    scale, good, hyst = state

    hyst_after_overflow = jnp.maximum(hyst - 1, 0)
    reduce_now = hyst_after_overflow == 0
    scale_on_overflow = jnp.where(
        reduce_now, jnp.maximum(scale / 2.0, min_loss_scale), scale)
    hyst_on_overflow = jnp.where(reduce_now,
                                 jnp.asarray(init_hysteresis, jnp.int32),
                                 hyst_after_overflow)

    good_next = good + 1
    window_hit = good_next >= loss_scale_window
    scale_on_good = jnp.where(window_hit, scale * 2.0, scale)
    good_on_good = jnp.where(window_hit, 0, good_next)
    hyst_on_good = (jnp.asarray(init_hysteresis, jnp.int32)
                    if consecutive_hysteresis else hyst)

    return LossScaleState(
        loss_scale=jnp.where(overflow, scale_on_overflow, scale_on_good),
        good_steps=jnp.where(overflow, 0, good_on_good),
        hysteresis=jnp.where(overflow, hyst_on_overflow, hyst_on_good),
    )


def has_inf_or_nan(tree) -> jnp.ndarray:
    """Global overflow check (reference ``stage3.py:2188 _has_inf_or_nan``) —
    a single fused reduction over every gradient leaf."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    return jnp.any(jnp.stack(flags))


def global_norm(tree) -> jnp.ndarray:
    """Global L2 norm across every leaf (sharded arrays reduce globally under
    GSPMD — no explicit psum needed)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))


def clip_by_global_norm(tree, max_norm: float, norm: jnp.ndarray = None) -> Tuple:
    """Scale gradients so their global norm is at most ``max_norm``
    (reference engine grad clipping semantics)."""
    if norm is None:
        norm = global_norm(tree)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * factor.astype(g.dtype), tree), norm


DTYPE_MAP = {
    "float32": jnp.float32,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "fp32": jnp.float32,
    "fp16": jnp.float16,
    "bf16": jnp.bfloat16,
}


def compute_dtype_from_config(cfg) -> jnp.dtype:
    return DTYPE_MAP[cfg.precision_dtype]


def cast_tree(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree)
