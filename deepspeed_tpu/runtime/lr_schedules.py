"""Learning-rate schedules.

Re-implements the reference schedule zoo (``deepspeed/runtime/lr_schedules.py``:
``LRRangeTest``, ``OneCycle``, ``WarmupLR``, ``WarmupDecayLR``,
``WarmupCosineLR``) as pure ``step -> lr`` functions compatible with optax,
plus thin stateful class wrappers exposing the reference's
``step()/get_lr()/state_dict()`` API for the engine.  Params keep the
reference JSON names (``warmup_min_lr``, ``cycle_first_step_size``, ...).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

LR_SCHEDULE_NAMES = ("LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR",
                     "WarmupCosineLR")

ScheduleFn = Callable[[int], float]


# ---------------------------------------------------------------------------
# Pure schedule builders
# ---------------------------------------------------------------------------


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_ignored) -> ScheduleFn:
    def fn(step: int) -> float:
        interval = (step // lr_range_test_step_size if lr_range_test_staircase
                    else step / lr_range_test_step_size)
        return lr_range_test_min_lr * (1.0 + lr_range_test_step_rate * interval)
    return fn


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log",
              **_ignored) -> ScheduleFn:
    warmup_num_steps = max(2, warmup_num_steps)

    def fn(step: int) -> float:
        if step >= warmup_num_steps:
            return warmup_max_lr
        if warmup_type == "log":
            frac = math.log(step + 1) / math.log(warmup_num_steps)
        else:
            frac = step / warmup_num_steps
        frac = min(max(frac, 0.0), 1.0)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac
    return fn


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_ignored) -> ScheduleFn:
    wl = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def fn(step: int) -> float:
        if step < warmup_num_steps:
            return wl(step)
        # linear decay to 0 over the remaining steps (reference WarmupDecayLR)
        span = max(1, total_num_steps - warmup_num_steps)
        frac = max(0.0, 1.0 - (step - warmup_num_steps) / span)
        return warmup_max_lr * frac
    return fn


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 1e-4,
                     warmup_type: str = "log", lr: float = 1.0,
                     **_ignored) -> ScheduleFn:
    """Cosine decay from peak ``lr`` to ``lr * cos_min_ratio`` after warmup
    from ``lr * warmup_min_ratio`` (reference ``WarmupCosineLR``)."""
    warmup_num_steps = max(2, warmup_num_steps)

    def fn(step: int) -> float:
        if step < warmup_num_steps:
            if warmup_type == "log":
                frac = math.log(step + 1) / math.log(warmup_num_steps)
            else:
                frac = step / warmup_num_steps
            ratio = warmup_min_ratio + (1.0 - warmup_min_ratio) * min(max(frac, 0.0), 1.0)
            return lr * ratio
        span = max(1, total_num_steps - warmup_num_steps)
        progress = min(1.0, (step - warmup_num_steps) / span)
        cos = 0.5 * (1.0 + math.cos(math.pi * progress))
        ratio = cos_min_ratio + (1.0 - cos_min_ratio) * cos
        return lr * ratio
    return fn


def one_cycle(cycle_min_lr: float = 0.0, cycle_max_lr: float = 0.001,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0,
              **_ignored) -> ScheduleFn:
    """Triangular cycle then optional decay (reference ``OneCycle``; momentum
    cycling is not applicable — optax momentum is part of the transform)."""
    second = cycle_second_step_size or cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def fn(step: int) -> float:
        if step < cycle_first_step_size:
            frac = step / cycle_first_step_size
            return cycle_min_lr + (cycle_max_lr - cycle_min_lr) * frac
        if step < total_cycle:
            frac = (step - cycle_first_step_size) / second
            return cycle_max_lr - (cycle_max_lr - cycle_min_lr) * frac
        # decay phase
        if decay_step_size > 0:
            decay_steps = (step - total_cycle) / decay_step_size
            return cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
        return cycle_min_lr
    return fn


_BUILDERS: Dict[str, Callable[..., ScheduleFn]] = {
    "LRRangeTest": lr_range_test,
    "OneCycle": one_cycle,
    "WarmupLR": warmup_lr,
    "WarmupDecayLR": warmup_decay_lr,
    "WarmupCosineLR": warmup_cosine_lr,
}


def get_schedule_fn(name: Optional[str], params: Dict[str, Any],
                    base_lr: Optional[float] = None) -> ScheduleFn:
    """Build a ``step -> lr`` fn from a reference-style scheduler config."""
    if name is None:
        lr = base_lr if base_lr is not None else 1e-3
        return lambda step: lr
    if name not in _BUILDERS:
        raise ValueError(f"Unknown scheduler type {name!r}; expected one of "
                         f"{LR_SCHEDULE_NAMES}")
    kwargs = dict(params)
    if name == "WarmupCosineLR" and base_lr is not None:
        kwargs.setdefault("lr", base_lr)
    return _BUILDERS[name](**kwargs)


# ---------------------------------------------------------------------------
# Stateful wrapper (reference class API)
# ---------------------------------------------------------------------------


class LRScheduler:
    """Stateful view over a schedule fn, exposing the reference's
    ``step()/get_lr()/get_last_lr()/state_dict()/load_state_dict()``."""

    def __init__(self, schedule_fn: ScheduleFn, last_batch_iteration: int = -1):
        self.schedule_fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self) -> List[float]:
        return [self.schedule_fn(max(0, self.last_batch_iteration))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def state_dict(self) -> Dict[str, Any]:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.last_batch_iteration = sd["last_batch_iteration"]
