"""NVMe optimizer-state swapping — the ZeRO-Infinity tier.

TPU-native re-design of the reference swap-tensor stack
(``runtime/swap_tensor/partitioned_optimizer_swapper.py:37``,
``optimizer_utils.py``, backed by ``csrc/aio``): Adam moments live on
local SSD/NVMe, not in HBM or host RAM.  Each train step streams them
through the device in flat contiguous BUCKETS (single-process; one
bucket per transformer layer so every layer reuses one compiled
program):

    read bucket(k+1..k+B-1) from NVMe ─┐ overlapped (native AIO threads)
    update bucket k on device          ─┤ one dispatch, one bulk copy each way
    write bucket(k-1) back to NVMe     ─┘ async, bounded in-flight

as a true three-stage software pipeline (reference
``pipelined_optimizer_swapper.py:47`` — double-buffered swap-in /
swap-out around the compute stage): a pool of ``buffer_count``
page-aligned pinned-host read buffers keeps up to ``B-1`` bucket reads
in flight ahead of the compute, write-back drains behind it under a
bounded in-flight budget, and the FIRST window's reads plus the LAST
buckets' write-backs overlap fwd/bwd of the surrounding steps
(:meth:`NvmeOptimizerSwapper.start_prefetch`, called by the engine
right after dispatching the grad step, and the deferred write-back
drained at the next step's stream start).  A failed async write retries
through the blocking path with jittered backoff before the stream
invalidates.  Per-stage waits (``swap_in_wait`` / ``bucket_update`` /
``swap_out_wait``) are measured every apply and surfaced through
``stage_stats`` / the engine's ``wall_clock_breakdown`` — the
link-boundedness of the stream is observable, not asserted.

A leaf-at-a-time stream is latency-bound (measured 0.014 GB/s vs ~1
GB/s bulk on the same AIO engine); the bucketed stream is
bandwidth-bound.  Multi-process jobs fall back to the leafwise stream,
where each rank swaps only its own addressable shards.  HBM and host
RAM hold O(buffer_count * bucket), not O(model).

Every byte the stream reads back is VERIFIED before it reaches the
optimizer update (silent-data-corruption defense; ``resilience.sdc``
config block): the write pipeline digests each bucket (and each
leafwise shard) on a side thread as the write is in flight, stores the
digest in the swapper metadata, and re-checks it on swap-in — the
read-side digests are likewise computed under the read-ahead window so
verification rides the existing latency hiding rather than extending
the critical path (``swap_verify_s`` in ``stage_stats`` is the
measured residual).  A mismatch escalates through a tiered recovery:
(1) blocking re-read with jittered backoff (transient host-buffer/DMA
corruption heals, bit-identically to an uninjected run), then (2) the
swap file is quarantined (``*.quarantine``) and
:class:`~deepspeed_tpu.resilience.guards.SwapCorruptionError` raises
through the engine's preemption/emergency-checkpoint path so the
elastic layer restarts from the last verified checkpoint instead of
training on garbage.  ``faults.hook`` sites ``swap.read_bucket`` /
``swap.read_item`` (kind ``bitflip``) drive the chaos and unit tests.

The optimizer math is the Adam/AdamW family only (the reference swapper
equally assumes a ``DeepSpeedCPUAdam``-style optimizer whose state is
two moments per parameter); the engine falls back to device-resident
state, with a warning, for anything else.
"""
from __future__ import annotations

import atexit
import hashlib
import os
import re
import shutil
import time
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.resilience.guards import SwapCorruptionError
from deepspeed_tpu.telemetry.metrics import metrics as _registry_metrics
from deepspeed_tpu.utils.logging import log_dist, logger


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True        # exists, owned by someone else — leave it alone
    return True


_SWAP_DIR_SEQ = iter(range(1 << 62))


def _swap_dir_name() -> str:
    # host+pid scoped: the liveness probe in _prune_stale_swap_dirs is
    # os.kill, which only means anything for OUR host's pids — on a mount
    # shared across hosts, a bare-pid name would let host B rmtree host A's
    # live swap dir just because A's pid happens to be unused on B.
    # The per-process sequence number keeps MULTIPLE swappers in one
    # process (e.g. an engine resumed next to its predecessor) from
    # aliasing each other's moment files — the SDC verifier caught two
    # engines silently stomping a shared dir's files exactly this way.
    import socket

    return (f"zero_stage_nvme_opt.{socket.gethostname()}.{os.getpid()}"
            f".{next(_SWAP_DIR_SEQ)}")


def _prune_stale_swap_dirs(root: str) -> None:
    """Best-effort removal of this host's ``zero_stage_nvme_opt.<host>.<pid>``
    dirs whose owning process is dead (crashed/killed runs never reach
    teardown).  Other hosts' dirs are never touched (their pids are
    unknowable here); pid recycling can keep a stale dir alive — harmless,
    it is reclaimed once that pid dies."""
    import socket

    host = re.escape(socket.gethostname())
    try:
        entries = os.listdir(root)
    except OSError:
        return
    for name in entries:
        # with or without the per-process sequence suffix (older dirs)
        m = re.fullmatch(rf"zero_stage_nvme_opt\.{host}\.(\d+)(?:\.\d+)?",
                         name)
        if not m or _pid_alive(int(m.group(1))):
            continue
        path = os.path.join(root, name)
        logger.info(f"pruning stale NVMe swap dir {path}")
        shutil.rmtree(path, ignore_errors=True)


def _close_weak(ref) -> None:
    swapper = ref()
    if swapper is not None:
        swapper.close()


def _norm_index(index, shape) -> tuple:
    """Normalize a shard's ``.index`` (tuple of slices) to a hashable
    ((start, stop), ...) key."""
    out = []
    for s, dim in zip(index, shape):
        if isinstance(s, slice):
            out.append((int(s.start or 0),
                        int(dim if s.stop is None else s.stop)))
        else:
            out.append((int(s), int(s) + 1))
    return tuple(out)


def _idx_tag(idx_norm: tuple) -> str:
    return hashlib.sha1(repr(idx_norm).encode()).hexdigest()[:8]


def _unique_shards(leaf) -> dict:
    """{normalized index -> one representative shard} over this process's
    addressable shards (replicated leaves repeat the same index on every
    local device — IO happens once per distinct slice)."""
    seen = {}
    for sh in leaf.addressable_shards:
        key = _norm_index(sh.index, leaf.shape)
        seen.setdefault(key, sh)
    return seen


def _to_device_space(x):
    """Move a pinned_host-resident array into device memory (leaf-wise —
    the swap loop's streaming granularity); anything else passes
    through."""
    sh = getattr(x, "sharding", None)
    if sh is not None and getattr(sh, "memory_kind", None) == "pinned_host":
        return jax.device_put(x, sh.with_memory_kind("device"))
    return x


def _float_leaf(x) -> bool:
    return jnp.issubdtype(np.asarray(x).dtype if not hasattr(x, "dtype")
                          else x.dtype, jnp.floating)


def _full_tag(shape) -> str:
    """Shard tag of the full-extent (single unique shard) index."""
    return _idx_tag(tuple((0, int(d)) for d in shape))


def _item_base(key: str) -> str:
    """Moment-file base name for a param key — the one naming scheme
    every tier (NVMe leafwise/bucketed, host-moment) and the checkpoint
    format share; the hash suffix keeps the map injective ("/"→"__"
    alone would collide for module names containing literal "__")."""
    digest = hashlib.sha1(key.encode()).hexdigest()[:8]
    return f"{key.replace('/', '__')}-{digest}"


def _item_fname(dirpath: str, item: dict) -> str:
    """Per-item moment file path for a bucket-plan item (same name the
    leafwise tier's ``_shard_fname`` produces for the full-extent
    shard)."""
    return os.path.join(dirpath,
                        f"{_item_base(item['key'])}.{item['tag']}.bin")


def _item_mv(data: np.ndarray, item: dict, n_total: int):
    """``(m, v)`` views of one item inside a flat ``[2 * n_total]``
    bucket buffer — the ONE place that knows the bucket layout."""
    o, n = item["off"], item["n"]
    return data[o:o + n], data[n_total + o:n_total + o + n]


def _write_item_file(dst: str, m, v) -> None:
    """Atomically write one item's ``[m; v]`` file (fp32, m then v —
    the shared checkpoint/leafwise layout).  Transient OSErrors (the
    NVMe mount hiccuping under checkpoint load) retry with jittered
    backoff; the tmp+rename makes every retry idempotent."""
    from deepspeed_tpu.resilience import faults
    from deepspeed_tpu.resilience.retry import retriable

    @retriable(retry_on=(OSError,))
    def _write():
        faults.hook("swap.write_item", path=dst)
        tmp = f"{dst}.tmp.p{jax.process_index()}"
        with open(tmp, "wb") as f:
            f.write(np.ascontiguousarray(m, np.float32).tobytes())
            f.write(np.ascontiguousarray(v, np.float32).tobytes())
        os.replace(tmp, dst)

    _write()


def _write_item_files_bulk(handle, dirpath: str, entries) -> None:
    """Write many items' ``[m; v]`` files through the AIO engine at
    once — the bulk replacement for the old one-``_write_item_file``-at-
    a-time loop (per-item sync writes are latency-bound exactly like the
    leafwise moment stream was; N items in flight run in the file
    bench's bandwidth regime).  ``entries`` is ``[(item, m, v), ...]``
    with fp32 views.  Atomicity per item is preserved (tmp + rename
    after the waits); an item whose async write fails falls back to the
    sync retriable path."""
    pend = []
    for it, m, v in entries:
        dst = _item_fname(dirpath, it)
        try:
            from deepspeed_tpu.resilience import faults

            faults.hook("swap.write_item", path=dst)
            m32 = np.ascontiguousarray(m, np.float32)
            v32 = np.ascontiguousarray(v, np.float32)
            tmp = f"{dst}.tmp.p{jax.process_index()}"
            from deepspeed_tpu.io.aio import _pretruncate

            _pretruncate(tmp, m32.nbytes + v32.nbytes, exact=True)
            ops = (handle.async_pwrite(m32, tmp, 0, _truncate=False),
                   handle.async_pwrite(v32, tmp, m32.nbytes,
                                       _truncate=False))
            pend.append((dst, tmp, ops, m, v))
        except OSError:
            _write_item_file(dst, m, v)         # sync + retriable
    for dst, tmp, ops, m, v in pend:
        ok = True
        for op in ops:
            try:
                handle.wait(op)
            except OSError:
                ok = False
        if ok:
            os.replace(tmp, dst)
        else:
            _write_item_file(dst, m, v)


def _read_item_files_bulk(handle, entries) -> None:
    """Fill many items' ``(m, v)`` views from their ``[m; v]`` files
    through the AIO engine at once (bulk counterpart of the old
    per-item ``np.fromfile`` loop).  Missing files are skipped (their
    views keep whatever the caller zero-initialized); a failed async
    read falls back to a sync ``np.fromfile``."""
    pend = []
    for fname, it, m, v in entries:
        if not os.path.exists(fname):
            continue
        ops = (handle.async_pread(m, fname, 0),
               handle.async_pread(v, fname, 4 * it["n"]))
        pend.append((fname, it, m, v, ops))
    for fname, it, m, v, ops in pend:
        ok = True
        for op in ops:
            try:
                handle.wait(op)
            except OSError:
                ok = False
        if not ok:
            raw = np.fromfile(fname, dtype=np.float32)
            m[:] = raw[:it["n"]]
            v[:] = raw[it["n"]:2 * it["n"]]


def _copy_atomic(src: str, dst: str) -> None:
    """Per-process tmp + atomic rename copy (concurrent multi-host
    saves never interleave writes to one destination path — fragile on
    e.g. NFS), retried on transient OSError."""
    from deepspeed_tpu.resilience.retry import retriable

    @retriable(retry_on=(OSError,))
    def _copy():
        tmp = f"{dst}.tmp.p{jax.process_index()}"
        shutil.copy2(src, tmp)
        os.replace(tmp, dst)

    _copy()


def _plan_buckets(meta, bucket_bytes: int):
    """``(buckets, plan_keys, item_loc)`` for a flat-moment stream —
    the ONE plan-construction path both swapped tiers share (so their
    bucket layouts, and therefore their checkpoint item files, can never
    drift apart).  Honors the ``DSTPU_SWAP_BUCKET_MB`` override."""
    env_mb = os.environ.get("DSTPU_SWAP_BUCKET_MB")
    if env_mb:
        bucket_bytes = int(env_mb) << 20
    buckets = _build_bucket_plan(meta, bucket_bytes)
    plan_keys = {it["key"] for b in buckets for it in b["items"]}
    item_loc = {}
    for b in buckets:
        for it in b["items"]:
            item_loc[it["key"]] = (b["bid"], it["off"], it["tag"],
                                   it["n"], b["n"])
    return buckets, plan_keys, item_loc


def _build_bucket_plan(meta, cap_bytes: int):
    """Pack the float leaves into contiguous flat-moment buckets.

    Leaves are grouped by the digit-tuple in their path ("one bucket per
    transformer layer"): every layer bucket then has the IDENTICAL
    (shapes, dtypes, shardings) signature, so jax compiles ONE update
    program and reuses it for all layers — the bucketed stream costs a
    handful of XLA compilations, not one per bucket.  Groups larger than
    ``cap_bytes`` of ``[m; v]`` split greedily at leaf boundaries (the
    split points depend only on sizes, so identical groups still split
    identically).  A single leaf larger than the cap gets its own
    bucket."""
    groups: Dict[tuple, list] = {}
    order = []
    for key, (_base, shape, _dt) in meta.items():
        nums = tuple(re.findall(r"\d+", key))
        if nums not in groups:
            groups[nums] = []
            order.append(nums)
        groups[nums].append((key, shape))
    packed = []
    for nums in order:
        cur, cur_bytes = [], 0
        for key, shape in groups[nums]:
            n = int(np.prod(shape)) if shape else 1
            nb = 2 * n * 4                      # fp32 m + v
            if cur and cur_bytes + nb > cap_bytes:
                packed.append(cur)
                cur, cur_bytes = [], 0
            cur.append((key, shape, n))
            cur_bytes += nb
        if cur:
            packed.append(cur)
    buckets = []
    for bid, items in enumerate(packed):
        off, its = 0, []
        for key, shape, n in items:
            its.append({"key": key, "shape": tuple(int(d) for d in shape),
                        "n": n, "off": off, "tag": _full_tag(shape)})
            off += n
        buckets.append({"bid": bid, "items": its, "n": off})
    return buckets


def _adam_math(p, g, m, v, count, lr, gscale, b1, b2, eps, wd, adam_w):
    """One leaf's AdamW update (reference ``csrc/adam`` kernel math /
    ``optax.scale_by_adam`` + decoupled decay).  ``gscale`` folds the
    1/(loss_scale*gas) unscale and the clip coefficient; ``adam_w``
    selects decoupled (True) vs L2 (folded into the gradient) decay.
    Shared by the per-leaf and bucketed swap paths — one source of truth
    for the moment recurrence."""
    g = g.astype(jnp.float32) * gscale
    g = jnp.where(adam_w, g, g + wd * p)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * jnp.square(g)
    m_hat = m / (1.0 - b1 ** count)
    v_hat = v / (1.0 - b2 ** count)
    u = m_hat / (jnp.sqrt(v_hat) + eps)
    u = jnp.where(adam_w, u + wd * p, u)
    p_new = (p - lr * u).astype(p.dtype)
    return p_new, m, v


@partial(jax.jit, donate_argnums=(2, 3))
def _adam_update(p, g, m, v, count, lr, gscale, b1, b2, eps, wd, adam_w):
    return _adam_math(p, g, m, v, count, lr, gscale, b1, b2, eps, wd,
                      adam_w)


def _to_dev(x, flag):
    """In-program transfer of a host-space operand into device memory
    (XLA does not auto-stream host-resident inputs into compute ops);
    ``flag`` is resolved at trace time from the caller's placements."""
    if not flag:
        return x
    from deepspeed_tpu.utils.sharding import memory_space

    return jax.device_put(x, memory_space("device"))


def _bucket_adam(ps, gs, mv, count, lr, gscale, *, shapes, b1, b2, eps,
                 wd, adam_w, host_ps=(), host_gs=(), host_mv=False):
    """One BUCKET's update in a single XLA program: ``mv`` is the flat
    ``[m; v]`` stream for every leaf in the bucket (shape ``[2, n]``,
    fp32), sliced per leaf inside the program.  This is the TPU
    counterpart of the reference's flat-partition swap buffers
    (``swap_tensor/partitioned_optimizer_swapper.py:35`` — moments live
    as one contiguous range, not one tensor per file): one dispatch, one
    host→device copy and one device→host copy per bucket instead of per
    leaf, which is what turns a latency-bound leaf loop into a
    bandwidth-bound stream."""
    p_news, m_news, v_news = [], [], []
    host_ps = host_ps or (False,) * len(ps)
    host_gs = host_gs or (False,) * len(gs)
    mv = _to_dev(mv, host_mv)
    off = 0
    for p, g, shp, hp, hg in zip(ps, gs, shapes, host_ps, host_gs):
        n = 1
        for d in shp:
            n *= d
        m = mv[0, off:off + n].reshape(shp)
        v = mv[1, off:off + n].reshape(shp)
        p_new, m_new, v_new = _adam_math(
            _to_dev(p, hp), _to_dev(g, hg), m, v, count, lr, gscale,
            b1, b2, eps, wd, adam_w)
        p_news.append(p_new)
        m_news.append(m_new.ravel())
        v_news.append(v_new.ravel())
        off += n
    cat = (lambda xs: xs[0] if len(xs) == 1 else jnp.concatenate(xs))
    mv_new = jnp.stack([cat(m_news), cat(v_news)])
    return p_news, mv_new


def _bucket_adam_init(ps, gs, count, lr, gscale, *, shapes, b1, b2, eps,
                      wd, adam_w, host_ps=(), host_gs=()):
    """First-step variant of :func:`_bucket_adam`: zero moments are
    materialized INSIDE the program (no flat-moment input to transfer or
    pre-stage — also sidesteps AOT compilation of constant-only
    zero-fill programs)."""
    p_news, m_news, v_news = [], [], []
    host_ps = host_ps or (False,) * len(ps)
    host_gs = host_gs or (False,) * len(gs)
    for p, g, shp, hp, hg in zip(ps, gs, shapes, host_ps, host_gs):
        z = jnp.zeros(shp, jnp.float32)
        p_new, m_new, v_new = _adam_math(
            _to_dev(p, hp), _to_dev(g, hg), z, z, count, lr, gscale,
            b1, b2, eps, wd, adam_w)
        p_news.append(p_new)
        m_news.append(m_new.ravel())
        v_news.append(v_new.ravel())
    cat = (lambda xs: xs[0] if len(xs) == 1 else jnp.concatenate(xs))
    mv_new = jnp.stack([cat(m_news), cat(v_news)])
    return p_news, mv_new


class NvmeOptimizerSwapper:
    """Adam moments on NVMe, streamed through the device per step.

    One file per parameter leaf holding ``[m; v]`` contiguously in the
    master dtype; files are created lazily on the first successful step
    (zero-init moments never touch the disk).
    """

    def __init__(self, swap_dir: str, params: Any, *,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adam_w_mode: bool = True,
                 aio_block_size: int = 1 << 20,
                 aio_thread_count: int = 8,
                 aio_queue_depth: int = 128,
                 aio_use_odirect: bool = False,
                 bucket_bytes: int = 2 << 30,
                 pipeline_read: bool = True,
                 pipeline_write: bool = True,
                 buffer_count: int = 3,
                 sdc_verify: bool = True,
                 sdc_checksum: str = "sum64",
                 sdc_max_reread: int = 2):
        from deepspeed_tpu.io.aio import aio_handle

        # pid-scoped: two jobs pointing at the same NVMe mount must not
        # interleave moment files (swap state is transient — a resumed run
        # re-seeds its fresh dir from the checkpoint's nvme_optimizer/).
        # Swap state is worthless once its owning process is gone, so
        # (a) prune sibling dirs whose pids are dead before claiming ours
        # and (b) remove our own dir at exit — without this, long-lived
        # mounts accumulate dead 2x-fp32 moment sets until disk exhaustion.
        _prune_stale_swap_dirs(swap_dir)
        self.swap_dir = os.path.join(swap_dir, _swap_dir_name())
        os.makedirs(self.swap_dir, exist_ok=True)
        # weakref: an atexit handler holding `self` would pin every swapper
        # (and its native AIO thread pool) for process lifetime even after
        # its engine is dropped
        import weakref

        self._atexit = partial(_close_weak, weakref.ref(self))
        atexit.register(self._atexit)
        self._pending: list = []
        self._restored = False              # a load_from() succeeded
        self._reshard_warned = False
        self.handle = aio_handle(block_size=aio_block_size,
                                 thread_count=aio_thread_count,
                                 queue_depth=aio_queue_depth,
                                 use_odirect=aio_use_odirect)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.wd = float(weight_decay)
        self.adam_w_mode = bool(adam_w_mode)
        self.count = 0                      # successful (non-overflow) steps
        # -- pipeline shape (reference OffloadOptimizerConfig knobs:
        # pipeline_read / pipeline_write / buffer_count).  The read pool
        # holds `buffer_count` page-aligned host buffers; read-ahead is
        # bounded at buffer_count-1 by the reuse invariant (a slot is
        # reissued only after the compute that consumed its previous
        # tenant has been FORCED via its output fetch — an earlier reuse
        # would alias a buffer the in-flight dispatch may still read).
        # Write-back keeps at most buffer_count-1 bucket writes in
        # flight; pipeline_write additionally defers the trailing writes
        # past apply() so they drain under the NEXT step's fwd/bwd.
        # Both off => the strictly serial stream (the parity-test
        # reference: bit-identical state, no overlap).
        self.pipeline_read = bool(pipeline_read)
        self.pipeline_write = bool(pipeline_write)
        self._buffer_count = max(1, int(buffer_count))
        self._nbuf = max(2, int(buffer_count)) if self.pipeline_read else 1
        self._write_depth = (max(1, int(buffer_count) - 1)
                             if self.pipeline_write else 0)
        self._use_odirect = bool(aio_use_odirect)
        # live prefetch marker: how many bucket reads the read-ahead
        # window already carries into the next apply() (None = no
        # prefetch outstanding)
        self._prefetched: Optional[int] = None
        self._req_buffer_count: Optional[int] = None
        # -- silent-data-corruption defense (resilience.sdc): every
        # bucket/shard the stream writes is digested (on a side thread,
        # overlapped with the in-flight IO) and re-checked on swap-in
        # BEFORE the bytes reach the optimizer update.  Mismatch =>
        # blocking re-read retry, then quarantine + SwapCorruptionError.
        self._sdc_verify = bool(sdc_verify)
        self._sdc_algo = str(sdc_checksum)
        self._sdc_rereads = max(0, int(sdc_max_reread))
        self._bucket_sums: Dict[int, tuple] = {}   # kb -> (digest, nbytes)
        # (key, tag) -> ((m_digest, m_nbytes), (v_digest, v_nbytes))
        self._item_sums: Dict[tuple, tuple] = {}
        # in-flight digest jobs live on the shared bounded-async-stage
        # substrate (keyed submit / selective pop / forced settle) —
        # the executor inside stays unspun until the first deferred job
        self._sdc_pool = None                      # lazy DigestPool
        # cumulative detection/recovery telemetry (surfaced through
        # stage_stats and MonitorMaster.write_sdc_health)
        self.sdc_counters: Dict[str, int] = {
            "verified": 0, "mismatches": 0, "rereads": 0,
            "reread_recovered": 0, "quarantined": 0, "restore_rejected": 0}
        # per-apply stage telemetry (see _apply_bucketed); engine surfaces
        # it under wall_clock_breakdown and the bench infinity row.
        # Accumulation routes through the shared StageTimers substrate
        # (the one telemetry schema: <stage>_s floats + raw counters),
        # which also re-emits each stage as a tracer span when tracing
        # is on; stage_stats composes its snapshot with derived metrics
        from deepspeed_tpu.utils.async_stage import (BoundedAsyncStage,
                                                     StageTimers)
        self.stage_timers = StageTimers(cat="swap")
        # The read-ahead and write-back windows live on the shared
        # bounded-async-stage substrate (the same skeleton the serving
        # pipeline and the tiered KV store compose): the read window
        # holds up to ``_nbuf`` keyed bucket preads (poller-backed so
        # harvest can consume completed reads opportunistically, in
        # bucket order, without blocking on ones still in flight); the
        # write window bounds in-flight bucket write-backs at
        # ``_write_depth`` via submit back-pressure, and ops left in it
        # past apply() ARE the deferred write-backs (settled at the
        # forced-drain points: start_prefetch / the next apply / drain).
        # Both windows get their own timers so substrate-internal
        # brackets (submit_wait/drain) don't leak extra keys into
        # ``stage_stats`` — the stream's own t_in/t_out brackets below
        # keep the historical swap_in_wait/swap_out_wait meaning.
        self._reads = BoundedAsyncStage(
            waiter=self._read_waiter, poller=self._read_poller,
            depth=self._nbuf, timers=StageTimers(cat="swap"),
            name="swap_readahead")
        self._writes = BoundedAsyncStage(
            waiter=self._write_waiter, depth=max(1, self._write_depth),
            timers=StageTimers(cat="swap"), name="swap_writeback")
        self._swap_out_wait = 0.0           # waiter-side t_out accumulator
        self.stage_stats: Dict[str, Any] = {}
        # leafwise-stream IO accounting (incremented where reads/writes
        # are actually submitted; _apply_leafwise resets per apply and
        # reports read/write rates — the multi-process bench row)
        self._io_read_bytes = 0
        self._io_write_bytes = 0
        self._verify_wait_s = 0.0           # leafwise verify residual
        # (leaf key, shard index tag) pairs with moments on disk — THIS
        # process's shards only; other processes track their own
        self._initialized: set = set()
        # (key, tag) -> normalized ((start, stop), ...) slice ranges.
        # Tags are sha1 digests — non-invertible — so the geometry each
        # tag covers must travel explicitly for a checkpoint to be
        # re-sliceable at a different world size
        self._shard_idx: Dict[tuple, tuple] = {}
        # key -> [(tag, slices, checkpoint path, digests, algo)] over
        # EVERY process's swap_meta in the restored checkpoint — the
        # source material for re-bucketing moments after a world change
        self._saved_shards: Dict[str, list] = {}
        self._resharded_keys: set = set()
        # (key, tag) shards already rejected at restore — never re-read
        # (and never re-counted) by the re-slice path
        self._rejected_shards: set = set()
        # leaf registry: key -> (file basename, full shape, np dtype)
        self._meta: Dict[str, Tuple[str, tuple, np.dtype]] = {}
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        from deepspeed_tpu.checkpoint.sharded import path_str

        total = 0
        for kp, leaf in flat:
            if not _float_leaf(leaf):
                continue
            key = path_str(kp)
            # moments are ALWAYS fp32 on disk regardless of the param
            # (master) dtype — the update math promotes to fp32, and
            # sizing the layout by a bf16 param dtype would interleave
            # the m/v byte ranges
            dt = np.dtype(np.float32)
            base = os.path.join(self.swap_dir, _item_base(key))
            self._meta[key] = (base, tuple(leaf.shape), dt)
            total += 2 * int(np.prod(leaf.shape)) * dt.itemsize
        # bucketed fast path (single-process only — a flat bucket spans
        # leaves, which this process must own in full): moments stream as
        # large contiguous [m; v] buckets, one dispatch + one bulk copy
        # each way per bucket.  Multi-process jobs keep the per-shard
        # leafwise stream (each rank swaps its own partition).
        self._buckets = None
        self._bucket_ready: set = set()
        self._bucket_fns: Dict[tuple, Any] = {}
        self._read_bufs = None
        self._fallback_warned = False
        self._item_loc: Dict[str, tuple] = {}
        self._items_dirty = False
        if jax.process_count() == 1 and self._meta:
            self._buckets, self._plan_keys, self._item_loc = \
                _plan_buckets(self._meta, bucket_bytes)
            self._plan_hash = hashlib.sha1(repr(
                [(it["key"], it["shape"]) for b in self._buckets
                 for it in b["items"]]).encode()).hexdigest()[:8]
            n_sig = len({tuple(it["shape"] for it in b["items"])
                         for b in self._buckets})
            log_dist(f"NVMe optimizer swap: bucketed stream — "
                     f"{len(self._buckets)} buckets "
                     f"({n_sig} distinct programs), "
                     f"largest {max(2 * 4 * b['n'] for b in self._buckets) / 1e9:.2f} GB",
                     ranks=[0])
        log_dist(f"NVMe optimizer swap: {len(self._meta)} leaves, "
                 f"{total / 1e9:.2f} GB of moments (full tree) at "
                 f"{self.swap_dir}; this process swaps its addressable "
                 "shards", ranks=[0])

    # -- silent-data-corruption defense ----------------------------------

    # below this, a thread-pool round trip costs more than the digest
    # itself (sum64 runs ~9 GB/s) — small buffers digest inline
    _SDC_DEFER_MIN = 4 << 20

    def _pool(self):
        """Digest side pool (lazy), on the shared bounded-async-stage
        substrate: numpy/zlib checksums release the GIL, so write-side
        digests genuinely overlap the in-flight IO and the device
        compute instead of extending the stream's wall."""
        if self._sdc_pool is None:
            from deepspeed_tpu.resilience.sdc import DigestPool

            self._sdc_pool = DigestPool(
                algo=self._sdc_algo, workers=2,
                defer_min=self._SDC_DEFER_MIN)
        return self._sdc_pool

    def _digest(self, arr) -> tuple:
        from deepspeed_tpu.resilience.sdc import digest

        return digest(arr, self._sdc_algo)

    def _note_bucket_sum(self, kb: int, arr, defer: bool = True) -> None:
        """Record bucket ``kb``'s write-side digest.  ``defer``: compute
        on the side pool (the submitted buffer is immutable until the
        write is reaped, so the job races nothing)."""
        if not self._sdc_verify:
            return
        # the bucket's bytes changed: any per-item digests recorded by
        # an earlier spill/restore are stale now
        pool = self._pool()
        for it in self._buckets[kb]["items"]:
            self._item_sums.pop((it["key"], it["tag"]), None)
            pool.discard(("i", it["key"], it["tag"]))
        d = pool.note(("b", kb), arr, defer=defer)
        if d is not None:
            self._bucket_sums[kb] = d

    def _note_item_sums(self, key: str, tag: str, m, v,
                        defer: bool = True) -> None:
        """Record one item/shard's write-side ``(m, v)`` digests."""
        if not self._sdc_verify:
            return
        if defer and m.nbytes + v.nbytes >= self._SDC_DEFER_MIN:
            self._pool().submit(("i", key, tag),
                                lambda: (self._digest(m), self._digest(v)))
        else:
            self._item_sums[(key, tag)] = (self._digest(m),
                                           self._digest(v))

    def _settle_sums(self) -> None:
        """Fold finished side-thread digest jobs into the metadata maps
        (save/spill/restore paths need the full picture; the per-read
        verify gates use the SELECTIVE lookups below instead, so they
        never block on digests of unrelated in-flight writes)."""
        if self._sdc_pool is None:
            return
        for k, d in self._sdc_pool.settle().items():
            if k[0] == "b":
                self._bucket_sums[k[1]] = d
            else:
                self._item_sums[(k[1], k[2])] = d

    def _expected_bucket_sum(self, kb: int) -> Optional[tuple]:
        if self._sdc_pool is not None and ("b", kb) in self._sdc_pool:
            self._bucket_sums[kb] = self._sdc_pool.pop(("b", kb))
        return self._bucket_sums.get(kb)

    def _expected_item_sums(self, key: str, tag: str) -> Optional[tuple]:
        if (self._sdc_pool is not None
                and ("i", key, tag) in self._sdc_pool):
            self._item_sums[(key, tag)] = self._sdc_pool.pop(
                ("i", key, tag))
        return self._item_sums.get((key, tag))

    def _sdc_clear(self) -> None:
        """Invalidation hook: a cleared swap state has no bytes left to
        verify (runs alongside ``_initialized/_bucket_ready`` clears)."""
        self._bucket_sums.clear()
        self._item_sums.clear()
        if self._sdc_pool is not None:
            self._sdc_pool.clear()

    def _quarantine_file(self, fname: str) -> str:
        """Move a checksum-failing swap file aside (never delete — the
        corrupt bytes matter for postmortem, exactly like the
        checkpoint layer's ``<tag>.corrupt`` quarantine)."""
        self.sdc_counters["quarantined"] += 1
        dst = fname + ".quarantine"
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f"{fname}.quarantine.{n}"
        try:
            os.rename(fname, dst)
        except OSError:
            dst = fname                     # already gone; nothing to keep
        logger.error(f"NVMe swap: QUARANTINED corrupt swap file "
                     f"{os.path.basename(fname)} -> "
                     f"{os.path.basename(dst)}")
        return dst

    def _verify_bucket_view(self, kb: int, view: np.ndarray,
                            got: Optional[tuple] = None) -> None:
        """Check a just-read bucket against its stored digest; on
        mismatch escalate: (1) blocking re-reads with jittered backoff
        (transient host-buffer/DMA corruption heals here), (2)
        quarantine + :class:`SwapCorruptionError` (persistent on-media
        corruption — the engine aborts to the last verified
        checkpoint).  No-op when verification is off or the bucket has
        no recorded digest (nothing trustworthy to compare against)."""
        if not self._sdc_verify:
            return
        expect = self._expected_bucket_sum(kb)
        if expect is None:
            return
        if (got or self._digest(view)) == expect:
            self.sdc_counters["verified"] += 1
            return
        self.sdc_counters["mismatches"] += 1
        fname = self._bucket_fname(kb)
        logger.error(
            f"NVMe swap: checksum MISMATCH on bucket {kb} swap-in "
            f"({os.path.basename(fname)}); re-reading "
            f"(max {self._sdc_rereads} retries)")
        from deepspeed_tpu.resilience import faults
        from deepspeed_tpu.resilience.retry import retriable

        @retriable(attempts=self._sdc_rereads + 1,
                   retry_on=(SwapCorruptionError,))
        def _reread():
            self.sdc_counters["rereads"] += 1
            action = faults.hook("swap.read_bucket", path=fname)
            self.handle.sync_pread(view, fname)
            if action is not None and action[0] == "bitflip":
                faults.apply_bitflip(view, action[1])
            if self._digest(view) != expect:
                raise SwapCorruptionError(
                    f"bucket {kb} ({os.path.basename(fname)}) failed "
                    f"checksum verification (algo={self._sdc_algo})")

        try:
            _reread()
        except SwapCorruptionError as err:
            self._quarantine_file(fname)
            self._bucket_ready.discard(kb)
            self._bucket_sums.pop(kb, None)
            from deepspeed_tpu.telemetry import flight

            flight.dump_on_fault("swap_corruption", err,
                                 extra={"bucket": int(kb),
                                        "file": os.path.basename(fname)})
            raise
        self.sdc_counters["reread_recovered"] += 1
        logger.warning(f"NVMe swap: bucket {kb} re-read clean — "
                       "transient corruption recovered")

    def _read_bucket_verified(self, kb: int, data: np.ndarray) -> None:
        """Blocking bucket read + verification — the non-pipelined read
        path (spill to item files, checkpoint save) shares the hot
        path's detection story: corrupt moments must not propagate into
        item files or checkpoints either."""
        from deepspeed_tpu.resilience import faults

        fname = self._bucket_fname(kb)
        action = faults.hook("swap.read_bucket", path=fname)
        self.handle.sync_pread(data, fname)
        if action is not None and action[0] == "bitflip":
            faults.apply_bitflip(data, action[1])
        self._verify_bucket_view(kb, data)

    def _verify_item_read(self, key: str, tag: str, m: np.ndarray,
                          v: np.ndarray, src: tuple) -> None:
        """Leafwise counterpart of :meth:`_verify_bucket_view` for one
        shard's ``(m, v)`` pair; ``src = (fname, off_m, off_v)`` names
        the re-read source."""
        if not self._sdc_verify:
            return
        expect = self._expected_item_sums(key, tag)
        if expect is None:
            return
        if (self._digest(m), self._digest(v)) == expect:
            self.sdc_counters["verified"] += 1
            return
        self.sdc_counters["mismatches"] += 1
        fname, off_m, off_v = src
        logger.error(
            f"NVMe swap: checksum MISMATCH on moment shard {key!r} "
            f"swap-in ({os.path.basename(fname)}); re-reading")
        from deepspeed_tpu.resilience import faults
        from deepspeed_tpu.resilience.retry import retriable

        @retriable(attempts=self._sdc_rereads + 1,
                   retry_on=(SwapCorruptionError,))
        def _reread():
            self.sdc_counters["rereads"] += 1
            action = faults.hook("swap.read_item", path=fname, key=key)
            self.handle.sync_pread(m, fname, off_m)
            self.handle.sync_pread(v, fname, off_v)
            if action is not None and action[0] == "bitflip":
                faults.apply_bitflip(m, action[1])
            if (self._digest(m), self._digest(v)) != expect:
                raise SwapCorruptionError(
                    f"moment shard {key!r} ({os.path.basename(fname)}) "
                    f"failed checksum verification "
                    f"(algo={self._sdc_algo})")

        try:
            _reread()
        except SwapCorruptionError as err:
            self._quarantine_file(fname)
            self._initialized.discard((key, tag))
            self._item_sums.pop((key, tag), None)
            from deepspeed_tpu.telemetry import flight

            flight.dump_on_fault("swap_corruption", err,
                                 extra={"key": key,
                                        "file": os.path.basename(fname)})
            raise
        self.sdc_counters["reread_recovered"] += 1
        logger.warning(f"NVMe swap: shard {key!r} re-read clean — "
                       "transient corruption recovered")

    # -- per-step IO ----------------------------------------------------

    # Moment files are PER ADDRESSABLE SHARD: ``<leaf>.<index-tag>.bin``.
    # Each process reads/writes only the slices its devices own, which is
    # what lifts the old single-controller restriction — a multi-host job
    # swaps its local ZeRO shards and never materializes a full leaf
    # (reference partitioned_optimizer_swapper semantics: every rank swaps
    # its own partition).

    def _shard_fname(self, key: str, tag: str) -> str:
        return f"{self._meta[key][0]}.{tag}.bin"

    def start_read(self, key: str, leaf) -> Dict[tuple, Optional[tuple]]:
        """Begin async moment reads for every distinct local shard of
        ``leaf``; entries are None where moments are zero-init."""
        dt = self._meta[key][2]
        loc = self._item_loc.get(key)
        if loc is not None and self._writes.in_flight:
            # a deferred write-back may still be in flight against the
            # bucket file this read targets — settle it first
            self._drain_deferred()
        out: Dict[tuple, Optional[tuple]] = {}
        uniq = _unique_shards(leaf)
        if self._restored and key in self._saved_shards \
                and key not in self._resharded_keys:
            missing = [idx for idx in uniq
                       if (key, _idx_tag(idx)) not in self._initialized]
            if missing:
                # the restored checkpoint's shard tags don't match the
                # CURRENT layout (world changed since save): re-slice
                # this leaf from the saved slice records before falling
                # back to zero-init
                self._resharded_keys.add(key)
                self._reshard_key(key, missing)
        for idx, sh in uniq.items():
            tag = _idx_tag(idx)
            if (loc is not None and tag == loc[2]
                    and loc[0] in self._bucket_ready
                    and (key, tag) in self._initialized):
                # moments live inside a flat bucket file — read the
                # item's m/v ranges straight out of it
                kb, off, _tag, n_it, n_total = loc
                shp = tuple(sh.data.shape)
                m = np.empty(shp, dt)
                v = np.empty(shp, dt)
                fname = self._bucket_fname(kb)
                out[idx] = (
                    self.handle.async_pread(m, fname, 4 * off),
                    self.handle.async_pread(v, fname, 4 * (n_total + off)),
                    m, v, (fname, 4 * off, 4 * (n_total + off)))
                self._io_read_bytes += m.nbytes + v.nbytes
                continue
            if (key, tag) not in self._initialized:
                if self._restored and not self._reshard_warned:
                    # the re-slice above could not produce this shard —
                    # either the checkpoint predates slice records (only
                    # full-extent tags are recognizable then) or every
                    # covering saved file failed verification (counted
                    # in restore_rejected) — so this moment restarts
                    # zero, loudly
                    self._reshard_warned = True
                    logger.warning(
                        f"NVMe swap: restored moment set has no shard "
                        f"for {key!r} under the CURRENT sharding and it "
                        "could not be re-sliced from the saved records; "
                        "affected moments restart from zero")
                out[idx] = None
                continue
            shp = tuple(sh.data.shape)
            nbytes = int(np.prod(shp)) * dt.itemsize
            m = np.empty(shp, dt)
            v = np.empty(shp, dt)
            fname = self._shard_fname(key, tag)
            out[idx] = (self.handle.async_pread(m, fname, 0),
                        self.handle.async_pread(v, fname, nbytes), m, v,
                        (fname, 0, nbytes))
            self._io_read_bytes += 2 * nbytes
        return out

    def finish_read(self, key: str, leaf, started) -> Tuple[Any, Any]:
        """Join the shard reads and assemble GLOBAL moment arrays with the
        param leaf's sharding (each process contributes its local
        shards)."""
        from deepspeed_tpu.resilience import faults

        dt = self._meta[key][2]
        vals: Dict[tuple, Tuple[np.ndarray, np.ndarray]] = {}
        for idx, st in started.items():
            if st is None:
                shp = tuple(b - a for a, b in idx)
                vals[idx] = (np.zeros(shp, dt), np.zeros(shp, dt))
            else:
                import time as _time

                op_m, op_v, m, v, src = st
                self.handle.wait(op_m)
                self.handle.wait(op_v)
                action = faults.hook("swap.read_item", path=src[0],
                                     key=key)
                if action is not None and action[0] == "bitflip":
                    faults.apply_bitflip(m, action[1])
                t0 = _time.perf_counter()
                self._verify_item_read(key, _idx_tag(idx), m, v, src)
                self._verify_wait_s += _time.perf_counter() - t0
                vals[idx] = (m, v)
        shards = leaf.addressable_shards
        m_parts = [jax.device_put(vals[_norm_index(s.index, leaf.shape)][0],
                                  s.device) for s in shards]
        v_parts = [jax.device_put(vals[_norm_index(s.index, leaf.shape)][1],
                                  s.device) for s in shards]
        spec = jax.sharding.NamedSharding(
            leaf.sharding.mesh, leaf.sharding.spec) \
            if hasattr(leaf.sharding, "spec") else leaf.sharding
        m_dev = jax.make_array_from_single_device_arrays(
            leaf.shape, spec, m_parts)
        v_dev = jax.make_array_from_single_device_arrays(
            leaf.shape, spec, v_parts)
        return m_dev, v_dev

    def write(self, key: str, m_new, v_new) -> None:
        """Write this process's shards of the updated moments."""
        dt = self._meta[key][2]
        from deepspeed_tpu.io.aio import _pretruncate

        v_shards = _unique_shards(v_new)
        for idx, m_sh in _unique_shards(m_new).items():
            tag = _idx_tag(idx)
            fname = self._shard_fname(key, tag)
            m_np = np.ascontiguousarray(np.asarray(m_sh.data), dtype=dt)
            v_np = np.ascontiguousarray(np.asarray(v_shards[idx].data),
                                        dtype=dt)
            _pretruncate(fname, 2 * m_np.nbytes, exact=False)
            self._pending.append(self.handle.async_pwrite(
                m_np, fname, 0, _truncate=False))
            self._pending.append(self.handle.async_pwrite(
                v_np, fname, m_np.nbytes, _truncate=False))
            # write-side digest on the side pool — the buffers are
            # pinned by the write queue until the ops are reaped, so
            # the job races nothing and rides the in-flight IO
            self._note_item_sums(key, tag, m_np, v_np)
            self._io_write_bytes += m_np.nbytes + v_np.nbytes
            self._initialized.add((key, tag))
            self._shard_idx[(key, tag)] = idx
            if self._buckets is not None and key in self._plan_keys:
                # a leafwise write of a plan key leaves moments in item
                # files — the next bucketed step must fold them back in
                # (even when no bucket existed yet to spill)
                self._items_dirty = True

    def drain(self) -> None:
        """Wait EVERY pending write (even after one fails — a raised
        ``wait`` means that op finished; abandoning the rest would leave
        live IO racing later writes to the same files), then re-raise the
        first failure.  Covers both the leafwise stream's per-shard
        writes and the pipeline's deferred bucket write-backs."""
        first_err = None
        try:
            for op in self._pending:
                try:
                    self.handle.wait(op)
                except Exception as e:       # op completed (failed); keep going
                    first_err = first_err or e
        finally:
            self._pending = []
        try:
            self._drain_deferred()
        except Exception as e:
            first_err = first_err or e
        if first_err is not None:
            raise first_err

    def close(self) -> None:
        """Drain in-flight IO and delete the swap dir (moments are
        transient — resumable state lives in the checkpoint's
        ``nvme_optimizer/``, not here).  Idempotent; registered atexit
        (via weakref) and safe to call from engine teardown."""
        self.cancel_prefetch()
        try:
            self.drain()
        except Exception:
            pass
        if self._sdc_pool is not None:
            self._sdc_pool.close()
            self._sdc_pool = None
        shutil.rmtree(self.swap_dir, ignore_errors=True)
        try:
            atexit.unregister(self._atexit)
        except Exception:
            pass

    # -- the step --------------------------------------------------------

    def apply(self, params: Any, grads: Any, *, lr, gscale) -> Any:
        """Update every float leaf in ``params`` against ``grads``;
        returns the new params tree.  Single-process runs stream the
        moments in flat buckets (one dispatch + one bulk host↔device
        copy per bucket — bandwidth-bound); multi-process runs, or a
        params tree that doesn't match the registered plan, stream
        leaf-by-leaf (each rank swaps its own shards)."""
        if self._buckets is not None:
            flat = jax.tree_util.tree_flatten_with_path(params)[0]
            from deepspeed_tpu.checkpoint.sharded import path_str

            fkeys = {path_str(kp) for kp, leaf in flat
                     if _float_leaf(leaf)}
            shardable = all(hasattr(leaf, "sharding") for kp, leaf in flat
                            if _float_leaf(leaf))
            if fkeys == self._plan_keys and shardable:
                return self._apply_bucketed(params, grads, lr=lr,
                                            gscale=gscale)
            if not self._fallback_warned:
                self._fallback_warned = True
                logger.warning(
                    "NVMe swap: params tree doesn't match the bucketed "
                    "plan (subset call or non-jax leaves) — using the "
                    "leafwise stream for this call")
            # keep the two on-disk layouts coherent: materialize the
            # affected buckets as item files first (the leafwise stream
            # reads/writes item files), reassembled lazily on the next
            # bucketed step
            self.cancel_prefetch()
            self._spill_buckets_to_items(fkeys & self._plan_keys)
        return self._apply_leafwise(params, grads, lr=lr, gscale=gscale)

    def _spill_buckets_to_items(self, keys) -> None:
        """Write the bucket-resident moments of ``keys`` out as per-item
        files and retire those buckets (leafwise IO takes over for
        them).  Item writes go through the bulk AIO path — one pass per
        bucket, all item files in flight together."""
        self._drain_deferred()
        kbs = sorted({self._item_loc[k][0] for k in keys
                      if k in self._item_loc})
        for kb in kbs:
            if kb not in self._bucket_ready:
                continue
            b = self._buckets[kb]
            data = np.empty(2 * b["n"], np.float32)
            # verified read: corrupt bucket bytes must not propagate
            # into item files (detected here, not N steps later)
            self._read_bucket_verified(kb, data)
            entries = [(it,) + _item_mv(data, it, b["n"])
                       for it in b["items"]
                       if (it["key"], it["tag"]) in self._initialized]
            _write_item_files_bulk(self.handle, self.swap_dir, entries)
            for it, m, v in entries:
                self._note_item_sums(it["key"], it["tag"], m, v,
                                     defer=False)
            os.remove(self._bucket_fname(kb))
            self._bucket_ready.discard(kb)
            self._bucket_sums.pop(kb, None)
            self._items_dirty = True

    def _bucket_fname(self, kb: int) -> str:
        return os.path.join(self.swap_dir,
                            f"bucket_{kb:04d}.{self._plan_hash}.bin")

    def _bucket_call(self, bucket, ps, gs):
        """The jitted flat-bucket update for this bucket's signature;
        identical-structure buckets (all transformer layers) share one
        compiled program via the cache key."""
        shapes = tuple(it["shape"] for it in bucket["items"])
        out_sh = tuple(p.sharding for p in ps)
        host_ps = tuple(getattr(p.sharding, "memory_kind", None)
                        == "pinned_host" for p in ps)
        host_gs = tuple(getattr(getattr(g, "sharding", None),
                                "memory_kind", None) == "pinned_host"
                        for g in gs)
        mv_sh = ps[0].sharding
        if isinstance(mv_sh, jax.sharding.NamedSharding):
            mv_sh = jax.sharding.NamedSharding(
                mv_sh.mesh, jax.sharding.PartitionSpec())
        if getattr(mv_sh, "memory_kind", None) == "pinned_host":
            mv_sh = mv_sh.with_memory_kind("device")
        key = (shapes, out_sh, mv_sh, host_ps, host_gs)
        fn = self._bucket_fns.get(key)
        if fn is None:
            fn = jax.jit(
                partial(_bucket_adam, shapes=shapes, b1=self.b1,
                        b2=self.b2, eps=self.eps, wd=self.wd,
                        adam_w=self.adam_w_mode,
                        host_ps=host_ps, host_gs=host_gs),
                out_shardings=(list(out_sh), mv_sh))
            self._bucket_fns[key] = fn
        return fn

    # -- the software pipeline -------------------------------------------

    def _ensure_read_bufs(self) -> None:
        if self._read_bufs is None:
            from deepspeed_tpu.io.aio import aligned_empty

            mx = max(b["n"] for b in self._buckets)
            # page-aligned so the O_DIRECT read path engages without a
            # bounce copy when aio.use_odirect is set
            self._read_bufs = [aligned_empty(2 * mx, np.float32)
                               for _ in range(self._nbuf)]

    def _issue_read(self, kb: int) -> Optional[tuple]:
        """Start bucket ``kb``'s NVMe read into its pool slot; None when
        the bucket has no file yet (zero-init moments)."""
        if kb not in self._bucket_ready:
            return None
        b = self._buckets[kb]
        view = self._read_bufs[kb % self._nbuf][:2 * b["n"]]
        return (self.handle.async_pread(view, self._bucket_fname(kb), 0),
                view)

    # window adapters: the substrate only knows ``op``s — for reads
    # that is the ``(aio_op, staged view)`` pair _issue_read returns
    # (or None for a zero-init bucket: no file, no IO, joins
    # instantly), for writes the ``(aio_op, pinned array, kb)`` triple
    # _finish_write's retry path needs.

    def _read_waiter(self, st: Optional[tuple]) -> Optional[np.ndarray]:
        if st is None:
            return None
        self.handle.wait(st[0])
        return st[1]

    def _read_poller(self, st: Optional[tuple]) -> bool:
        return st is None or self.handle.poll(st[0]) is not None

    def _write_waiter(self, ent: tuple) -> None:
        op, arr, kb = ent
        t0 = time.perf_counter()
        self._finish_write(op, arr, kb)
        self._swap_out_wait += time.perf_counter() - t0

    # -- the buffer_count knob (runtime-safe) ----------------------------

    @property
    def buffer_count(self) -> int:
        return self._buffer_count

    def set_buffer_count(self, n: int) -> None:
        """Resize the read/write windows at the next safe point (the
        next apply()/prefetch entry with no read-ahead in flight) —
        the controller's runtime knob.  Numerics are unaffected: the
        pipelined and serial streams are bit-identical by the parity
        contract, and the window shape only changes overlap."""
        self._req_buffer_count = max(1, int(n))

    def _apply_requested_buffer_count(self) -> None:
        if self._req_buffer_count is None or self._reads.in_flight:
            return
        n, self._req_buffer_count = self._req_buffer_count, None
        if n == self._buffer_count:
            return
        self._buffer_count = n
        self._nbuf = max(2, n) if self.pipeline_read else 1
        self._write_depth = (max(1, n - 1) if self.pipeline_write else 0)
        self._read_bufs = None              # re-sized lazily
        self._reads.depth = self._nbuf
        self._writes.depth = max(1, self._write_depth)

    def start_prefetch(self) -> None:
        """Issue the first read-ahead window's bucket reads (and settle
        any write-backs deferred from the previous step) so the stream's
        head overlaps the fwd/bwd the engine has just dispatched — the
        pipeline's first stage starts before the grads exist.  No-op
        unless the bucketed pipelined stream will run; harmless when the
        step later overflows (:meth:`cancel_prefetch`)."""
        if (self._buckets is None or not self.pipeline_read
                or self._prefetched is not None or self._items_dirty):
            return
        try:
            self._drain_deferred()
        except Exception:
            # invalidation is already logged and the state reset; the
            # apply() that follows streams zero-init moments — don't
            # kill the in-flight fwd/bwd from a prefetch
            return
        self._apply_requested_buffer_count()
        self._ensure_read_bufs()
        n = min(self._nbuf, len(self._buckets))
        for kb in range(n):
            self._reads.submit(kb, self._issue_read(kb))
        self._prefetched = n

    def cancel_prefetch(self) -> None:
        """Settle prefetched reads without consuming them (overflow
        skipped the step, or the stream fell back leafwise)."""
        self._prefetched = None
        for key in self._reads.keys():
            try:
                self._reads.pop(key)
            except Exception:
                pass

    def _submit_bucket_write(self, kb: int, arr: np.ndarray) -> int:
        from deepspeed_tpu.io.aio import _pretruncate
        from deepspeed_tpu.resilience import faults

        fname = self._bucket_fname(kb)
        action = faults.hook("swap.write_bucket", path=fname)
        if action is not None and action[0] == "torn":
            # honor the torn-write directive: a fraction of the bytes
            # reach the disk, then the "process dies" — the stream's
            # invalidation contract must cover it
            with open(fname, "wb") as f:
                f.write(arr.tobytes()[:max(1, int(arr.nbytes
                                                  * action[1]))])
            raise faults.SimulatedCrash(
                f"[fault-injection] torn bucket write at {fname}")
        _pretruncate(fname, arr.nbytes, exact=False)
        return self.handle.async_pwrite(arr, fname, 0, _truncate=False)

    def _sync_rewrite_bucket(self, kb: int, arr: np.ndarray) -> None:
        """Blocking rewrite with jittered backoff — the retry path
        behind a failed async bucket write.  Idempotent (full rewrite
        from offset 0) so every retry is safe; exhausting the budget
        re-raises and the caller invalidates."""
        from deepspeed_tpu.resilience import faults
        from deepspeed_tpu.resilience.retry import retriable

        fname = self._bucket_fname(kb)

        @retriable(retry_on=(OSError,))
        def _write():
            faults.hook("swap.write_bucket", path=fname)
            self.handle.sync_pwrite(arr, fname, 0)

        _write()

    def _finish_write(self, op: int, arr: np.ndarray, kb: int) -> None:
        """Join one async bucket write; a failed op retries through the
        blocking path before giving up (arr is the submitted buffer,
        still pinned by the write queue — no aliasing with later
        buckets' staging)."""
        try:
            self.handle.wait(op)
        except OSError:
            self._sync_rewrite_bucket(kb, arr)

    def _drain_deferred(self) -> None:
        """Settle write-backs deferred past a previous apply() (they
        have been draining under the fwd/bwd dispatched since).  A
        persistent failure means that bucket's on-disk moments are STALE
        relative to params the step already committed — invalidate
        (moments restart zero-init) and re-raise."""
        if self._writes.in_flight == 0:
            return
        try:
            # drain(): joins EVERYTHING even after one fails, raising
            # the first error only after the sweep — the invalidation
            # contract (no op left racing a reused buffer)
            self._writes.drain()
        except Exception:
            logger.error(
                "NVMe swap: deferred bucket write-back failed after its "
                "step committed — on-disk moments are stale; "
                "invalidating swap state (moments restart zero-init; "
                "reload the checkpoint to recover real state)")
            self._initialized.clear()
            self._bucket_ready.clear()
            self._sdc_clear()
            raise

    def _apply_bucketed(self, params: Any, grads: Any, *, lr,
                        gscale) -> Any:
        """Three-stage pipelined flat-bucket moment stream (reference
        ``pipelined_optimizer_swapper.py:47`` semantics): while bucket k
        updates on device, the reads of buckets k+1..k+B-1 are in
        flight on the AIO threads and bucket k-1's write-back drains
        behind a bounded budget — each bucket moves host↔device as ONE
        array.  Per-stage blocked time is measured into ``stage_stats``
        every call.  Failure invalidates the swap state exactly like the
        leafwise path (moments restart zero-init)."""
        import time as _time

        from deepspeed_tpu.checkpoint.sharded import path_str
        from deepspeed_tpu.io.aio import aligned_empty

        prefetched, self._prefetched = self._prefetched, None
        try:
            self._drain_deferred()
        except Exception:
            self.cancel_prefetch()
            raise
        if self._items_dirty:
            # a leafwise fallback wrote item files for plan keys — fold
            # them back into bucket files before streaming (prefetched
            # reads, if any, predate the fold and are discarded)
            self.cancel_prefetch()
            prefetched = None
            self._assemble_buckets_from_items()
            self._items_dirty = False
        if prefetched is None:
            # no read-ahead carried in: the safe point for a pending
            # buffer_count knob change (windows empty, writes drained)
            self._apply_requested_buffer_count()
        self.count += 1
        count = np.float32(self.count)
        lr = np.float32(lr)
        gscale = np.float32(gscale)
        flat_p = jax.tree_util.tree_flatten_with_path(params)
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        keys = [path_str(kp) for kp, _ in flat_p[0]]
        leaves = [leaf for _, leaf in flat_p[0]]
        idx = {k: i for i, k in enumerate(keys)}
        new_leaves = list(leaves)
        buckets = self._buckets
        nb = len(buckets)
        from deepspeed_tpu.resilience import faults as _faults

        self._ensure_read_bufs()
        pipelined = self._nbuf > 1
        t_in = t_up = t_out = t_verify = 0.0
        bytes_read = bytes_written = 0
        self._swap_out_wait = 0.0
        t_begin = _time.perf_counter()

        reads, writes = self._reads, self._writes
        next_issue = int(prefetched or 0)   # prefetch = reads 0..n-1 live
        ready: Dict[int, Optional[np.ndarray]] = {}   # harvested views
        verify_futs: Dict[int, Any] = {}              # kb -> digest future
        harvest_next = 0

        def issue_upto(limit: int) -> None:
            # slot-reuse invariant: bucket j reuses slot j % nbuf, whose
            # previous tenant was bucket j - nbuf — only re-issue once
            # that bucket's compute has been FORCED (its output fetch in
            # flush()), or an in-flight dispatch could still be reading
            # the buffer the new pread scribbles into.  The loop keeps
            # the read window at most ``_nbuf`` deep, so submit's own
            # back-pressure never fires (a forced join there would
            # consume a read outside harvest's bookkeeping).
            nonlocal next_issue
            while next_issue <= min(limit, nb - 1):
                reads.submit(next_issue, self._issue_read(next_issue))
                next_issue += 1

        def harvest(block_upto: int = -1) -> None:
            # pop completed reads, IN BUCKET ORDER, from the read
            # window into `ready`: the swap.read_bucket fault site
            # fires and the read-side digest job is submitted at
            # completion time, so verification runs on the side pool
            # while later buckets' IO and earlier buckets' compute are
            # still in flight — the check rides the read-ahead window,
            # not the critical path.  Buckets <= block_upto are waited;
            # later ones are harvested only if their read already
            # completed (the window's poller-backed ready()).
            nonlocal harvest_next, t_in, bytes_read
            while harvest_next < nb and harvest_next in reads:
                kb2 = harvest_next
                if kb2 > block_upto and not reads.ready(kb2):
                    break
                t0 = _time.perf_counter()
                view = reads.pop(kb2)
                t_in += _time.perf_counter() - t0
                harvest_next += 1
                if view is None:          # zero-init bucket: no file
                    ready[kb2] = None
                    continue
                bytes_read += view.nbytes
                action = _faults.hook("swap.read_bucket",
                                      path=self._bucket_fname(kb2))
                if action is not None and action[0] == "bitflip":
                    _faults.apply_bitflip(view, action[1])
                if (self._sdc_verify
                        and view.nbytes >= self._SDC_DEFER_MIN):
                    verify_futs[kb2] = self._pool().submit(
                        self._digest, view)
                ready[kb2] = view

        def flush(entry) -> None:
            nonlocal t_up, t_out, bytes_written
            kb, mv_out = entry
            t0 = _time.perf_counter()
            mv_np = np.asarray(mv_out)    # forces bucket kb's compute
            t_up += _time.perf_counter() - t0
            if self._use_odirect:
                # jax-owned output buffers aren't page-aligned; stage
                # through an aligned copy so the O_DIRECT write engages
                a = aligned_empty(mv_np.size, mv_np.dtype)
                a[:] = mv_np.ravel()
                mv_np = a
            try:
                op = self._submit_bucket_write(kb, mv_np)
            except OSError:
                # submit-time failure (e.g. preallocation): blocking
                # retry path, same as a failed in-flight op
                t0 = _time.perf_counter()
                self._sync_rewrite_bucket(kb, mv_np)
                t_out += _time.perf_counter() - t0
                op = None
            if op is not None:
                # submit's back-pressure IS the write bound: past
                # ``_write_depth`` in flight it joins the oldest first
                # (through the timed waiter — the old reap())
                writes.submit(kb, (op, mv_np, kb))
            # write-side digest on the side pool, overlapped with the
            # write it describes (mv_np is pinned by the write window
            # until joined, so the job races nothing)
            self._note_bucket_sum(kb, mv_np)
            bytes_written += mv_np.nbytes
            if self._write_depth == 0:
                writes.drain()            # serial mode: settle now
            self._bucket_ready.add(kb)
            for it in buckets[kb]["items"]:
                self._initialized.add((it["key"], it["tag"]))

        ok = False
        prev_out = None                   # (kb, mv_out device array)
        try:
            issue_upto(self._nbuf - 1)    # initial window: slots all fresh
            for kb, b in enumerate(buckets):
                if not pipelined:
                    # serial mode (parity reference): force compute k-1
                    # and settle its write BEFORE touching the single
                    # read buffer again
                    if prev_out is not None:
                        flush(prev_out)
                        prev_out = None
                    issue_upto(kb)
                if kb not in ready:
                    harvest(block_upto=kb)
                view = ready.pop(kb)
                if view is None:
                    mv_in = np.zeros((2, b["n"]), np.float32)
                else:
                    # swap-in verification gate: the digest job was
                    # submitted when the read completed (usually done
                    # by now); mismatch re-reads, then quarantines +
                    # raises — corrupt bytes never reach the update
                    if self._sdc_verify:
                        t0 = _time.perf_counter()
                        fut = verify_futs.pop(kb, None)
                        self._verify_bucket_view(
                            kb, view, got=fut.result() if fut else None)
                        t_verify += _time.perf_counter() - t0
                    else:
                        self._verify_bucket_view(kb, view, got=None)
                    mv_in = view.reshape(2, b["n"])
                ps = [leaves[idx[it["key"]]] for it in b["items"]]
                gs = [flat_g[idx[it["key"]]] for it in b["items"]]
                p_news, mv_out = self._bucket_call(b, ps, gs)(
                    ps, gs, mv_in, count, lr, gscale)
                for it, pn in zip(b["items"], p_news):
                    new_leaves[idx[it["key"]]] = pn
                # harvest BEFORE the flush below blocks forcing bucket
                # kb-1's compute: completed read-ahead buckets get their
                # digest jobs submitted now, so they run on the side
                # pool UNDER that block and are done when their turn's
                # verification gate checks them
                harvest()
                if pipelined and prev_out is not None:
                    flush(prev_out)       # forces compute kb-1 ...
                    issue_upto(kb - 1 + self._nbuf)   # ... freeing slots
                prev_out = (kb, mv_out)
            if prev_out is not None:
                flush(prev_out)
            if not self.pipeline_write:
                writes.drain()            # reap(0): settle every write
            # else: trailing write-backs stay in the write window and
            # drain under the NEXT step's fwd/bwd (settled at the
            # forced points: start_prefetch / the next apply / drain);
            # their buffers stay pinned by the window until joined
            ok = True
        finally:
            for key in reads.keys():
                try:
                    reads.pop(key)
                except Exception:
                    pass
            err = None
            if not ok and writes.in_flight:
                try:
                    writes.drain()
                except Exception as e:
                    err = e
            if not ok or err is not None:
                logger.error(
                    "NVMe optimizer bucketed apply() failed mid-stream; "
                    "on-disk moments are ahead of the params tree — "
                    "invalidating swap state (moments restart zero-init; "
                    "reload the checkpoint to recover real state)")
                self.count -= 1
                self._initialized.clear()
                self._bucket_ready.clear()
                self._sdc_clear()
            if ok and err is not None:
                raise err
        total = _time.perf_counter() - t_begin
        # the write window's waiter timed every join it performed
        # (back-pressure and drains alike) into the accumulator — that
        # plus the sync-fallback residual is the historical t_out
        t_out += self._swap_out_wait
        st = self.stage_timers
        st.reset()
        # swap_verify is the main-thread residual of swap-in
        # verification (the digest itself runs on the side pool under
        # the read-ahead window; this is what it adds to the critical
        # path)
        for name, secs in (("swap_in_wait", t_in), ("bucket_update", t_up),
                           ("swap_out_wait", t_out),
                           ("swap_verify", t_verify), ("apply", total)):
            st.add(name, secs)
        st.count("bytes_read", int(bytes_read))
        st.count("bytes_written", int(bytes_written))
        st.count("buckets", nb)
        self.stage_stats = {
            **st.snapshot(),
            # fraction of the stream's wall NOT blocked on NVMe waits —
            # ~1.0 means the disk hides behind compute/transfers (or
            # vice versa); a low value localizes which stage starves via
            # the stage times above
            "overlap_efficiency": (round(1.0 - min(1.0, (t_in + t_out)
                                                   / total), 4)
                                   if total > 0 else None),
            "stream_gbps": (round((bytes_read + bytes_written)
                                  / total / 1e9, 3) if total > 0 else None),
            "pipelined": pipelined,
            "sdc": dict(self.sdc_counters),   # cumulative
        }
        _registry_metrics.sync_counters(
            "dstpu_sdc_", self.sdc_counters,
            help="Swap-path SDC defense counters (cumulative)")
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), new_leaves)

    def _apply_leafwise(self, params: Any, grads: Any, *, lr,
                        gscale) -> Any:
        """Leaf-by-leaf stream: the next leaf's read overlaps the
        current leaf's update.

        A failure mid-loop leaves on-disk moments for already-processed
        leaves one step ahead of the abandoned params tree, so the swap
        state is INVALID after an exception escapes: in-flight IO is
        drained (finally) and ``_initialized`` is cleared, forcing
        zero-init moments (or a checkpoint reload) rather than silently
        mixing half-advanced state into a retried step."""
        from deepspeed_tpu.checkpoint.sharded import path_str

        import time as _time

        self.count += 1
        self._io_read_bytes = self._io_write_bytes = 0
        self._verify_wait_s = 0.0
        t_apply0 = _time.perf_counter()
        count = jnp.asarray(self.count, jnp.float32)
        lr = jnp.asarray(lr, jnp.float32)
        gscale = jnp.asarray(gscale, jnp.float32)
        flat_p = jax.tree_util.tree_flatten_with_path(params)
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        keys = [path_str(kp) for kp, _ in flat_p[0]]
        leaves = [leaf for _, leaf in flat_p[0]]
        todo = [i for i, leaf in enumerate(leaves) if _float_leaf(leaf)]

        started = {}
        ok = False
        try:
            if todo:
                i0 = todo[0]
                started[i0] = self.start_read(keys[i0], leaves[i0])
            new_leaves = list(leaves)
            for pos, i in enumerate(todo):
                if pos + 1 < len(todo):                 # prefetch next leaf
                    nxt = todo[pos + 1]
                    started[nxt] = self.start_read(keys[nxt], leaves[nxt])
                orig = leaves[i]
                # host-offloaded params/grads (ZeRO-Infinity composition)
                # stream through DEVICE memory one leaf at a time — jit
                # math can't mix host- and device-space operands
                p = _to_device_space(orig)
                g = _to_device_space(flat_g[i])
                m_dev, v_dev = self.finish_read(keys[i], p,
                                                started.pop(i))
                p_new, m_new, v_new = _adam_update(
                    p, g, m_dev, v_dev, count, lr, gscale,
                    self.b1, self.b2, self.eps, self.wd, self.adam_w_mode)
                if hasattr(orig, "sharding"):
                    # keep the ORIGINAL param's placement (incl. pinned_host
                    # when offload_param=cpu composes with the NVMe tier) —
                    # restoring against the device-space rebind would strand
                    # every updated leaf in HBM and OOM the offloaded config
                    p_new = jax.device_put(p_new, orig.sharding)
                new_leaves[i] = p_new
                self.write(keys[i], m_new, v_new)
            ok = True
        finally:
            # drain whatever was issued — leaked in-flight ops would race a
            # subsequent apply()/close() over the same files.  Cleanup waits
            # themselves can raise (that IS the failure mode being handled),
            # so every step is individually guarded: the `if not ok`
            # invalidation must run no matter what.
            for per_shard in started.values():
                for st in per_shard.values():
                    if st is None:
                        continue
                    for op in (st[0], st[1]):
                        try:
                            self.handle.wait(op)
                        except Exception:
                            pass             # op finished (failed read)
            drain_err = None
            try:
                self.drain()
            except Exception as e:           # a failed write corrupts a leaf
                drain_err = e
            if not ok or drain_err is not None:
                logger.error(
                    "NVMe optimizer apply() failed mid-stream; on-disk "
                    "moments are ahead of the params tree — invalidating "
                    "swap state (moments restart zero-init; reload the "
                    "checkpoint to recover real state)")
                self.count -= 1
                self._initialized.clear()
                self._bucket_ready.clear()
                self._sdc_clear()
            if ok and drain_err is not None:
                raise drain_err
        # per-shard leafwise stream telemetry: every rank reports ITS
        # partition's read/write rate (the multi-process analogue of the
        # bucketed path's stage_stats; wall is shared across overlapped
        # reads/writes so the per-direction rates are indicative, the
        # combined stream_gbps exact)
        wall = _time.perf_counter() - t_apply0
        st = self.stage_timers
        st.reset()
        # same schema as the bucketed path (StageTimers <stage>_s +
        # counters): apply_s is the shared wall key; wall_s stays as a
        # back-compat alias for the bench leafwise/multi-process rows
        for name, secs in (("apply", wall),
                           ("swap_verify", self._verify_wait_s)):
            st.add(name, secs)
        st.count("bytes_read", int(self._io_read_bytes))
        st.count("bytes_written", int(self._io_write_bytes))
        snap = st.snapshot()
        self.stage_stats = {
            "mode": "leafwise",
            **snap,
            "wall_s": snap["apply_s"],
            "read_gbps": round(self._io_read_bytes / wall / 1e9, 6)
            if wall > 0 else 0.0,
            "write_gbps": round(self._io_write_bytes / wall / 1e9, 6)
            if wall > 0 else 0.0,
            "stream_gbps": round((self._io_read_bytes
                                  + self._io_write_bytes) / wall / 1e9, 6)
            if wall > 0 else 0.0,
            "sdc": dict(self.sdc_counters),   # cumulative
        }
        _registry_metrics.sync_counters(
            "dstpu_sdc_", self.sdc_counters,
            help="Swap-path SDC defense counters (cumulative)")
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), new_leaves)

    # -- checkpoint integration ------------------------------------------

    def save_to(self, ckpt_dir: str) -> None:
        """Copy the moment files into ``ckpt_dir`` (they already live on
        disk — checkpointing the swapped state is a file copy, the same
        trick the reference plays when NVMe-offloaded state is checkpointed
        alongside, ``engine.py:3277``)."""
        out = os.path.join(ckpt_dir, "nvme_optimizer")
        os.makedirs(out, exist_ok=True)
        self.drain()
        self._settle_sums()
        # per-item digests travel with the checkpoint so a restore is
        # VERIFIED (a flipped bit in a checkpointed moment file is
        # rejected at load, not trained on): [key, tag, m_dig, v_dig]
        sums: list = []
        if self._buckets is not None:
            # bucketed store → per-item checkpoint files: the checkpoint
            # format stays topology-independent (a multi-host or leafwise
            # resume reads the same per-leaf [m; v] files).  One bulk
            # AIO pass per bucket — all of its item files in flight
            # together — instead of the old one-sync-write-per-item loop
            covered = set()
            for kb, b in enumerate(self._buckets):
                if kb not in self._bucket_ready:
                    continue
                data = np.empty(2 * b["n"], np.float32)
                # verified read: a corrupt bucket must not become the
                # "last verified checkpoint" the recovery relies on
                self._read_bucket_verified(kb, data)
                entries = []
                for it in b["items"]:
                    if (it["key"], it["tag"]) not in self._initialized:
                        continue
                    covered.add((it["key"], it["tag"]))
                    entries.append((it,) + _item_mv(data, it, b["n"]))
                _write_item_files_bulk(self.handle, out, entries)
                if self._sdc_verify:
                    for it, m, v in entries:
                        sums.append([it["key"], it["tag"],
                                     list(self._digest(m)),
                                     list(self._digest(v))])
            # spilled / foreign-tag items still have their own files
            for key, tag in self._initialized - covered:
                fname = self._shard_fname(key, tag)
                if not os.path.exists(fname):
                    continue
                dst = os.path.join(out, os.path.basename(fname))
                _copy_atomic(fname, dst)
                if (key, tag) in self._item_sums:
                    dm, dv = self._item_sums[(key, tag)]
                    sums.append([key, tag, list(dm), list(dv)])
        else:
            for key, tag in self._initialized:
                fname = self._shard_fname(key, tag)
                dst = os.path.join(out, os.path.basename(fname))
                # replicated leaves carry the same full-extent tag in
                # every process
                _copy_atomic(fname, dst)
                if (key, tag) in self._item_sums:
                    dm, dv = self._item_sums[(key, tag)]
                    sums.append([key, tag, list(dm), list(dv)])
        # one meta file per process: each process's shard set is disjoint
        # (multi-host swap — reference rank-local partition semantics)
        meta_name = f"swap_meta.p{jax.process_index()}.json"
        with open(os.path.join(out, meta_name), "w") as f:
            import json

            meta = {"count": self.count,
                    "initialized": sorted(list(t)
                                          for t in self._initialized),
                    "adam_w_mode": self.adam_w_mode,
                    "betas": [self.b1, self.b2], "eps": self.eps,
                    "weight_decay": self.wd}
            # explicit slice geometry per shard tag: the tag itself is a
            # hash, so without these records a checkpoint can only be
            # resumed at the EXACT topology that wrote it — with them a
            # world-change resume re-buckets the moments (load_from)
            shards = []
            for key, tag in sorted(self._initialized):
                idx = self._shard_idx.get((key, tag))
                if idx is None and tag == _full_tag(self._meta[key][1]):
                    idx = tuple((0, int(d)) for d in self._meta[key][1])
                if idx is not None:
                    shards.append([key, tag, [list(r) for r in idx]])
            if shards:
                meta["shards"] = shards
            if sums:
                meta["checksum_algo"] = self._sdc_algo
                meta["sums"] = sums
            json.dump(meta, f)

    def _load_legacy(self, src: str, meta_f: str) -> bool:
        """Restore a pre-shard-format checkpoint (``swap_meta.json`` with
        whole-leaf entries and whole-leaf moment files).  The old writer
        was single-controller and always dumped FULL arrays, so each old
        file maps onto the full-extent shard tag; layouts that shard a
        leaf won't match and fall back to zero-init with the reshard
        warning."""
        import json

        with open(meta_f) as f:
            meta = json.load(f)
        self.count = int(meta["count"])
        self._initialized = set()
        for key in meta["initialized"]:
            if key not in self._meta:
                logger.warning(f"swapped state for unknown param {key!r} "
                               "ignored")
                continue
            base, shape, _ = self._meta[key]
            tag = _idx_tag(tuple((0, d) for d in shape))
            old_name = os.path.basename(base) + ".bin"
            old_path = os.path.join(src, old_name)
            if not os.path.exists(old_path):
                logger.warning(f"legacy moment file {old_name} missing")
                continue
            shutil.copy2(old_path, self._shard_fname(key, tag))
            self._initialized.add((key, tag))
        self._restored = True
        self._assemble_buckets_from_items()
        logger.info(f"migrated legacy NVMe swap meta ({len(self._initialized)} "
                    "whole-leaf moment files)")
        return True

    def _assemble_buckets_from_items(self) -> None:
        """Fold restored per-item moment files into this plan's bucket
        files (bucketed mode only).  Items the checkpoint lacks — a
        topology change saved different shard tags — stay zero-init,
        matching the leafwise reshard semantics."""
        if self._buckets is None:
            return
        missing = 0
        for kb, b in enumerate(self._buckets):
            if kb in self._bucket_ready:
                continue                  # bucket file is authoritative
            for it in b["items"]:
                # bucketed items address the FULL leaf extent, so a
                # world-change checkpoint (per-shard tags) can always be
                # re-sliced up front from its saved slice records
                if (it["key"], it["tag"]) not in self._initialized \
                        and it["key"] in self._saved_shards \
                        and it["key"] not in self._resharded_keys:
                    self._resharded_keys.add(it["key"])
                    self._reshard_key(
                        it["key"],
                        [tuple((0, int(d)) for d in it["shape"])])
            present = [it for it in b["items"]
                       if (it["key"], it["tag"]) in self._initialized]
            missing += len(b["items"]) - len(present)
            if not present:
                continue
            data = np.zeros(2 * b["n"], np.float32)
            entries = [(self._shard_fname(it["key"], it["tag"]), it)
                       + _item_mv(data, it, b["n"]) for it in present]
            _read_item_files_bulk(self.handle, entries)
            for fname, it, m, v in entries:
                if not os.path.exists(fname):
                    continue
                # item files fold into the bucket verified — corrupt
                # restored/spilled moments escalate here, before they
                # become bucket-resident "truth"
                self._verify_item_read(it["key"], it["tag"], m, v,
                                       (fname, 0, m.nbytes))
            for fname, *_ in entries:
                if os.path.exists(fname):
                    os.remove(fname)
            self.handle.sync_pwrite(data, self._bucket_fname(kb))
            self._note_bucket_sum(kb, data, defer=False)
            self._bucket_ready.add(kb)
        if missing:
            logger.warning(
                f"NVMe swap: {missing} moment shards in the checkpoint "
                "don't match the current plan (topology changed since "
                "save); affected moments restart from zero")

    def load_from(self, ckpt_dir: str) -> bool:
        """Restore moment files saved by :meth:`save_to`; False when the
        checkpoint holds no swapped state (fresh moments)."""
        import json

        src = os.path.join(ckpt_dir, "nvme_optimizer")
        meta_f = os.path.join(
            src, f"swap_meta.p{jax.process_index()}.json")
        if not os.path.exists(meta_f):
            legacy = os.path.join(src, "swap_meta.json")
            if os.path.exists(legacy) and jax.process_index() == 0:
                return self._load_legacy(src, legacy)
            logger.warning("checkpoint has no NVMe-swapped optimizer state; "
                           "moments start fresh")
            return False
        with open(meta_f) as f:
            meta = json.load(f)
        saved = (tuple(meta.get("betas", (self.b1, self.b2))),
                 meta.get("eps", self.eps),
                 meta.get("weight_decay", self.wd),
                 meta.get("adam_w_mode", self.adam_w_mode))
        live = ((self.b1, self.b2), self.eps, self.wd, self.adam_w_mode)
        if saved != live:
            logger.warning(
                f"NVMe-swapped moments were produced with (betas, eps, wd, "
                f"adam_w_mode)={saved} but the live optimizer uses {live}; "
                "resuming applies the NEW coefficients to the old moments")
        self.count = int(meta["count"])
        self._initialized = set()
        ck_algo = meta.get("checksum_algo", self._sdc_algo)
        ck_sums = {(k, t): ((dm[0], dm[1]), (dv[0], dv[1]))
                   for k, t, dm, dv in meta.get("sums", [])}
        own_idx = {(k, t): tuple(tuple(int(x) for x in r) for r in sl)
                   for k, t, sl in meta.get("shards", [])}
        for entry in meta["initialized"]:
            key, tag = entry
            if key not in self._meta:
                logger.warning(f"swapped state for unknown param {key!r} "
                               "ignored")
                continue
            fname = self._shard_fname(key, tag)
            if not self._restore_item_file(
                    os.path.join(src, os.path.basename(fname)), fname,
                    key, tag, ck_sums.get((key, tag)), ck_algo):
                continue                    # rejected: restarts zero-init
            self._initialized.add((key, tag))
            if (key, tag) in own_idx:
                self._shard_idx[(key, tag)] = own_idx[(key, tag)]
        self._index_saved_shards(src)
        self._restored = True
        self._assemble_buckets_from_items()
        return True

    def _index_saved_shards(self, src: str) -> None:
        """Union EVERY process's ``swap_meta.p*.json`` slice records into
        ``_saved_shards`` — the raw material for re-slicing moments when
        the world changed between save and resume.  A world-W checkpoint
        read by world-W′ leaves per-process shard sets that no longer
        line up; the explicit (tag → slice ranges) records make each
        saved file addressable regardless of which process wrote it."""
        import glob as _glob
        import json

        self._saved_shards = {}
        self._resharded_keys = set()
        for meta_f in sorted(_glob.glob(
                os.path.join(src, "swap_meta.p*.json"))):
            try:
                with open(meta_f) as f:
                    m = json.load(f)
            except (OSError, ValueError) as e:
                logger.warning(f"unreadable swap meta {meta_f}: {e}")
                continue
            algo = m.get("checksum_algo", self._sdc_algo)
            sums = {(k, t): ((dm[0], dm[1]), (dv[0], dv[1]))
                    for k, t, dm, dv in m.get("sums", [])}
            recs = {(k, t): tuple(tuple(int(x) for x in r) for r in sl)
                    for k, t, sl in m.get("shards", [])}
            for entry in m.get("initialized", []):
                key, tag = entry
                if key not in self._meta \
                        or (key, tag) in self._rejected_shards:
                    continue
                slices = recs.get((key, tag))
                if slices is None:
                    # pre-record checkpoints: only the full-extent tag
                    # is recognizable (its index is a pure function of
                    # the shape); other tags stay layout-bound
                    shape = self._meta[key][1]
                    if tag != _full_tag(shape):
                        continue
                    slices = tuple((0, int(d)) for d in shape)
                path = os.path.join(src, os.path.basename(
                    self._shard_fname(key, tag)))
                self._saved_shards.setdefault(key, []).append(
                    (tag, slices, path, sums.get((key, tag)), algo))

    def _reshard_key(self, key: str, targets) -> bool:
        """Re-bucket one leaf's moments from the checkpoint's saved
        shard set onto the CURRENT layout: assemble the full fp32
        ``[m; v]`` leaf from every process's saved slices (each file
        digest-verified — a torn or stale shard is rejected, counted in
        ``restore_rejected``, and its range restarts zero), then cut and
        write the shard files ``targets`` (normalized indices) ask for.
        Only this one leaf is ever materialized in full.  Returns True
        when at least one target shard was produced."""
        from deepspeed_tpu.checkpoint.reshard import assemble_from_slices
        from deepspeed_tpu.resilience.sdc import checksum

        recs = self._saved_shards.get(key)
        if not recs:
            return False
        shape, dt = self._meta[key][1], self._meta[key][2]
        m_shards, v_shards = [], []
        rejected = 0
        for tag, slices, path, exp, algo in recs:
            try:
                data = np.fromfile(path, np.uint8)
            except OSError as e:
                logger.error(f"NVMe swap reshard: moment shard "
                             f"{os.path.basename(path)} unreadable ({e})")
                self.sdc_counters["restore_rejected"] += 1
                rejected += 1
                continue
            ext = tuple(int(b) - int(a) for a, b in slices)
            n = int(np.prod(ext)) if ext else 1
            nb = n * dt.itemsize
            m_b, v_b = data[:nb], data[nb:2 * nb]
            if data.nbytes != 2 * nb or (exp is not None and (
                    checksum(m_b, algo) != exp[0][0]
                    or checksum(v_b, algo) != exp[1][0])):
                self.sdc_counters["restore_rejected"] += 1
                self._rejected_shards.add((key, tag))
                rejected += 1
                logger.error(
                    f"NVMe swap reshard: saved moments for {key!r} "
                    f"shard {tag} FAILED verification; that range "
                    "restarts zero-init")
                continue
            m_shards.append((slices, m_b.view(dt)))
            v_shards.append((slices, v_b.view(dt)))
        if not m_shards:
            return False
        m_full, covered = assemble_from_slices(shape, m_shards, dtype=dt)
        v_full, _ = assemble_from_slices(shape, v_shards, dtype=dt)
        if not covered.all() and not rejected:
            # a hole WITHOUT a rejection means a process's meta/file
            # never made it into the checkpoint — surface it through the
            # same counter the acceptance contract watches (zeros must
            # never be silent; rejected shards already counted)
            self.sdc_counters["restore_rejected"] += 1
            logger.error(
                f"NVMe swap reshard: saved shards cover only "
                f"{int(covered.sum())}/{covered.size} elements of "
                f"{key!r}; uncovered moments restart zero-init")
        made = 0
        for idx in targets:
            idx = tuple(tuple(int(x) for x in r) for r in idx)
            tag = _idx_tag(idx)
            sl = tuple(slice(a, b) for a, b in idx)
            m_sl = np.ascontiguousarray(m_full[sl])
            v_sl = np.ascontiguousarray(v_full[sl])
            _write_item_file(self._shard_fname(key, tag), m_sl, v_sl)
            self._note_item_sums(key, tag, m_sl, v_sl, defer=False)
            self._initialized.add((key, tag))
            self._shard_idx[(key, tag)] = idx
            made += 1
        if made:
            logger.info(
                f"NVMe swap: re-sliced moments for {key!r} — {made} "
                f"shard(s) for the new layout from {len(m_shards)} saved "
                f"slice(s)" + (f", {rejected} rejected" if rejected
                               else ""))
            if _registry_metrics.enabled:
                _registry_metrics.counter(
                    "dstpu_swap_resharded_total",
                    "Moment leaves re-sliced across a world change"
                ).inc()
        return made > 0

    def _restore_item_file(self, src_path: str, dst: str, key: str,
                           tag: str, exp: Optional[tuple],
                           algo: str) -> bool:
        """Copy one checkpointed ``[m; v]`` moment file into the swap
        dir, VERIFIED against the digests the checkpoint recorded (a
        flipped bit in checkpointed moments is rejected at restore —
        that moment restarts zero with a loud error — instead of being
        trained on).  Files from checkpoints without digests copy
        unverified, as before."""
        from deepspeed_tpu.resilience.sdc import checksum

        try:
            data = np.fromfile(src_path, np.uint8)
        except OSError as e:
            logger.warning(f"moment file {os.path.basename(src_path)} "
                           f"unreadable ({e}); restarting zero-init")
            return False
        if exp is not None:
            (dm, nm), (dv, nv) = exp
            m, v = data[:nm], data[nm:nm + nv]
            if (data.nbytes != nm + nv or checksum(m, algo) != dm
                    or checksum(v, algo) != dv):
                self.sdc_counters["restore_rejected"] += 1
                self._rejected_shards.add((key, tag))
                logger.error(
                    f"NVMe swap: checkpointed moments for {key!r} FAILED "
                    f"checksum verification at restore "
                    f"({os.path.basename(src_path)}); rejected — this "
                    "moment restarts zero-init")
                return False
        tmp = f"{dst}.tmp.p{jax.process_index()}"
        data.tofile(tmp)
        os.replace(tmp, dst)
        if exp is not None and self._sdc_verify:
            if algo == self._sdc_algo:
                self._item_sums[(key, tag)] = exp
            else:
                (dm, nm), (dv, nv) = exp
                self._note_item_sums(key, tag, data[:nm],
                                     data[nm:nm + nv], defer=False)
        return True


class HostMomentSwapper:
    """ZeRO-Offload optimizer tier at streaming scale: Adam moments live
    in PINNED HOST memory as flat per-bucket arrays and update in one
    XLA program per bucket — every moment byte moves device↔host on the
    accelerator host's own link, never through the python client.

    This is the reference's CPU-Adam design point
    (``ops/adam/cpu_adam.py`` + ``zero/stage3.py`` offload_optimizer:
    moments in host DRAM, update overlapped with transfers) mapped to
    TPU: instead of an AVX CPU kernel, the chip updates each flat bucket
    between an H2D and D2H copy that XLA schedules; the donated input
    buffer makes the host-side moment store in-place.  The fused
    single-program alternative (``engine._build_train_step`` +
    ``fetch_opt``) materializes every gradient before the first moment
    write at 7B scale (measured 41G of HBM); bucket-wise dispatch keeps
    HBM at O(bucket).

    Same bucket plan and update math as :class:`NvmeOptimizerSwapper`
    (``_build_bucket_plan`` / ``_bucket_adam``), same per-item checkpoint
    format — a run can move between the host and NVMe tiers across
    resumes.  Single-process scope (multi-process jobs use the fused
    offload path or the NVMe tier's leafwise stream)."""

    def __init__(self, params: Any, *,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adam_w_mode: bool = True,
                 bucket_bytes: int = 2 << 30,
                 host_memory: bool = True):
        from deepspeed_tpu.checkpoint.sharded import path_str

        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.wd = float(weight_decay)
        self.adam_w_mode = bool(adam_w_mode)
        self.host_memory = bool(host_memory)
        self.count = 0
        self._meta: Dict[str, Tuple[str, tuple, np.dtype]] = {}
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        total = 0
        for kp, leaf in flat:
            if not _float_leaf(leaf):
                continue
            key = path_str(kp)
            self._meta[key] = ("", tuple(leaf.shape), np.dtype(np.float32))
            total += 2 * int(np.prod(leaf.shape)) * 4
        self._buckets, self._plan_keys, self._item_loc = \
            _plan_buckets(self._meta, bucket_bytes)
        self._mv: Dict[int, Any] = {}       # bid -> pinned_host [2, n]
        self._fns: Dict[tuple, Any] = {}
        self._io_handle = None              # lazy: checkpoint bulk IO only
        log_dist(f"host-offload optimizer stream: {len(self._buckets)} "
                 f"buckets, {total / 1e9:.2f} GB of moments in pinned "
                 "host memory", ranks=[0])

    def _host_sharding(self, like_leaf):
        sh = like_leaf.sharding
        if isinstance(sh, jax.sharding.NamedSharding):
            sh = jax.sharding.NamedSharding(sh.mesh,
                                            jax.sharding.PartitionSpec())
        if self.host_memory:
            sh = sh.with_memory_kind("pinned_host")
        return sh

    def _bucket_call(self, bucket, ps, gs, init: bool = False):
        shapes = tuple(it["shape"] for it in bucket["items"])
        out_sh = tuple(p.sharding for p in ps)
        host_ps = tuple(getattr(p.sharding, "memory_kind", None)
                        == "pinned_host" for p in ps)
        host_gs = tuple(getattr(getattr(g, "sharding", None),
                                "memory_kind", None) == "pinned_host"
                        for g in gs)
        mv_sh = self._host_sharding(ps[0])
        key = (shapes, out_sh, mv_sh, host_ps, host_gs, init)
        fn = self._fns.get(key)
        if fn is None:
            kw = dict(shapes=shapes, b1=self.b1, b2=self.b2,
                      eps=self.eps, wd=self.wd, adam_w=self.adam_w_mode,
                      host_ps=host_ps, host_gs=host_gs)
            if init:
                fn = jax.jit(partial(_bucket_adam_init, **kw),
                             out_shardings=(list(out_sh), mv_sh))
            else:
                fn = jax.jit(partial(_bucket_adam, host_mv=self.host_memory,
                                     **kw),
                             out_shardings=(list(out_sh), mv_sh),
                             donate_argnums=(2,))
            self._fns[key] = fn
        return fn

    def apply(self, params: Any, grads: Any, *, lr, gscale) -> Any:
        """Update every float leaf; moments stream host→device→host
        inside each bucket's program.  All dispatches are async — the
        runtime pipelines bucket k+1's H2D against bucket k's compute."""
        from deepspeed_tpu.checkpoint.sharded import path_str

        flat_p = jax.tree_util.tree_flatten_with_path(params)
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        keys = [path_str(kp) for kp, _ in flat_p[0]]
        leaves = [leaf for _, leaf in flat_p[0]]
        idx = {k: i for i, k in enumerate(keys)}
        fkeys = {k for k, leaf in zip(keys, leaves) if _float_leaf(leaf)}
        # validate BEFORE bumping count: a rejected call must not skew the
        # Adam bias correction of every later step
        if fkeys != self._plan_keys:
            raise ValueError(
                "host-offload optimizer: params tree does not match the "
                "registered plan (build the swapper over the same tree "
                "it updates)")
        self.count += 1
        count = np.float32(self.count)
        lr = np.float32(lr)
        gscale = np.float32(gscale)
        new_leaves = list(leaves)
        try:
            for kb, b in enumerate(self._buckets):
                ps = [leaves[idx[it["key"]]] for it in b["items"]]
                gs = [flat_g[idx[it["key"]]] for it in b["items"]]
                mv = self._mv.get(kb)
                if mv is None and getattr(self, "_pending_restore", None):
                    mv = self._materialize_restore(b, ps[0])
                if mv is None:
                    # first step: zero moments materialize inside the
                    # program
                    p_news, mv_new = self._bucket_call(
                        b, ps, gs, init=True)(ps, gs, count, lr, gscale)
                else:
                    p_news, mv_new = self._bucket_call(b, ps, gs)(
                        ps, gs, mv, count, lr, gscale)
                self._mv[kb] = mv_new
                for it, pn in zip(b["items"], p_news):
                    new_leaves[idx[it["key"]]] = pn
        except Exception:
            # buckets before the failure hold step-N+1 moments (and any
            # donated input is already consumed) while the params tree
            # stays at step N — same invalidation contract as the NVMe
            # tier: moments restart zero-init, reload a checkpoint to
            # recover real state
            logger.error(
                "host-moment optimizer apply() failed mid-stream; "
                "moments are ahead of the params tree — invalidating "
                "(moments restart zero-init)")
            self.count -= 1
            self._mv.clear()
            self._pending_restore = None
            raise
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), new_leaves)

    # -- checkpoint integration (NvmeOptimizerSwapper-compatible) --------

    def _io(self):
        """AIO handle for checkpoint-time bulk item IO (the per-step
        moment traffic never touches the disk in this tier)."""
        if self._io_handle is None:
            from deepspeed_tpu.io.aio import aio_handle

            self._io_handle = aio_handle(thread_count=4)
        return self._io_handle

    def save_to(self, ckpt_dir: str) -> None:
        """Write the per-item ``[m; v]`` files + meta — the same format
        :meth:`NvmeOptimizerSwapper.save_to` produces, so resumes are
        tier-agnostic."""
        import json

        out = os.path.join(ckpt_dir, "nvme_optimizer")
        os.makedirs(out, exist_ok=True)
        initialized = []
        pending = getattr(self, "_pending_restore", None)
        for kb, b in enumerate(self._buckets):
            mv = self._mv.get(kb)
            if mv is None:
                if pending is None:
                    continue
                # restored but not yet materialized (no step taken since
                # load): pass the restored item files through unchanged —
                # dropping them would save count=N over zero moments
                src, restored = pending
                for it in b["items"]:
                    if (it["key"], it["tag"]) not in restored:
                        continue
                    fname = os.path.join(
                        src, f"{_item_base(it['key'])}.{it['tag']}.bin")
                    if not os.path.exists(fname):
                        continue
                    dst = os.path.join(out, os.path.basename(fname))
                    if os.path.abspath(fname) != os.path.abspath(dst):
                        _copy_atomic(fname, dst)
                    initialized.append([it["key"], it["tag"]])
                continue
            data = np.asarray(mv).reshape(-1)
            entries = []
            for it in b["items"]:
                initialized.append([it["key"], it["tag"]])
                entries.append((it,) + _item_mv(data, it, b["n"]))
            _write_item_files_bulk(self._io(), out, entries)
        meta_name = f"swap_meta.p{jax.process_index()}.json"
        with open(os.path.join(out, meta_name), "w") as f:
            json.dump({"count": self.count,
                       "initialized": sorted(initialized),
                       "adam_w_mode": self.adam_w_mode,
                       "betas": [self.b1, self.b2], "eps": self.eps,
                       "weight_decay": self.wd}, f)

    def load_from(self, ckpt_dir: str) -> bool:
        """Restore per-item moment files into pinned-host buckets; False
        when the checkpoint holds no swapped state."""
        import json

        src = os.path.join(ckpt_dir, "nvme_optimizer")
        meta_f = os.path.join(src,
                              f"swap_meta.p{jax.process_index()}.json")
        if not os.path.exists(meta_f):
            logger.warning("checkpoint has no swapped optimizer state; "
                           "moments start fresh")
            return False
        with open(meta_f) as f:
            meta = json.load(f)
        self.count = int(meta["count"])
        restored = {tuple(e) for e in meta["initialized"]}
        self._pending_restore = (src, restored)
        return True

    def _materialize_restore(self, bucket, like_leaf):
        """Build one bucket's pinned-host mv from restored item files
        (missing items stay zero — topology-change semantics)."""
        src, restored = self._pending_restore
        n = bucket["n"]
        data = np.zeros(2 * n, np.float32)
        entries = [(_item_fname(src, it), it) + _item_mv(data, it, n)
                   for it in bucket["items"]
                   if (it["key"], it["tag"]) in restored]
        entries = [e for e in entries if os.path.exists(e[0])]
        if not entries:
            return None
        _read_item_files_bulk(self._io(), entries)
        return jax.device_put(data.reshape(2, n),
                              self._host_sharding(like_leaf))

    def close(self) -> None:
        self._mv.clear()


def _import_moments_nvme(self, fetch, count: int) -> int:
    """Ingest Adam moments from a FUSED-optimizer checkpoint (resume
    compat: a run that trained with device/fused offloaded opt_state and
    now resumes under a swapped-moment tier).  ``fetch(key)`` returns
    ``(mu, nu)`` numpy arrays or None; full-extent tags (single-process
    resumes; a multi-process leafwise resume re-shards from zero with
    the usual warning)."""
    n = 0
    for key, (_base, shape, _dt) in self._meta.items():
        got = fetch(key)
        if got is None:
            continue
        mu, nu = got
        tag = _full_tag(shape)
        _write_item_file(self._shard_fname(key, tag),
                         np.asarray(mu).reshape(-1),
                         np.asarray(nu).reshape(-1))
        self._initialized.add((key, tag))
        n += 1
    if n:
        self.count = int(count)
        self._restored = True
        self._assemble_buckets_from_items()
    return n


NvmeOptimizerSwapper.import_moments = _import_moments_nvme


def _import_moments_host(self, fetch, count: int) -> int:
    """Fused-checkpoint ingest for the host-moment tier: assemble each
    bucket's flat [m; v] from the checkpoint's mu/nu and place it in
    pinned host memory."""
    n = 0
    for kb, b in enumerate(self._buckets):
        data = None
        for it in b["items"]:
            got = fetch(it["key"])
            if got is None:
                continue
            if data is None:
                data = np.zeros(2 * b["n"], np.float32)
            mu, nu = got
            m, v = _item_mv(data, it, b["n"])
            m[:] = np.asarray(mu, np.float32).reshape(-1)
            v[:] = np.asarray(nu, np.float32).reshape(-1)
            n += 1
        if data is not None:
            self._mv[kb] = data.reshape(2, b["n"])   # device_put lazily
    if n:
        self.count = int(count)
        # numpy buckets upload on first use: the bucket program accepts
        # either (jit transfers the numpy input like the NVMe tier's)
    return n


HostMomentSwapper.import_moments = _import_moments_host
