"""NVMe optimizer-state swapping — the ZeRO-Infinity tier.

TPU-native re-design of the reference swap-tensor stack
(``runtime/swap_tensor/partitioned_optimizer_swapper.py:37``,
``optimizer_utils.py``, backed by ``csrc/aio``): Adam moments live on
local SSD/NVMe, not in HBM or host RAM.  Each train step streams them
through the device leaf-by-leaf:

    read moments(i+1) from NVMe   ─┐ overlapped (native AIO threads)
    update leaf i on device        ─┘
    write moments(i) back to NVMe  — async, drained at step end

The reference pipelines bucket reads/writes against CUDA streams
(``pipelined_optimizer_swapper.py``); here the overlap is host-side —
the AIO thread pool prefetches the next leaf's moments while XLA runs
the current leaf's fused update kernel.  HBM and host RAM hold O(largest
leaf), not O(model): the memory watermark the reference achieves with
swap buffers falls out of the double-buffered loop.

The optimizer math is the Adam/AdamW family only (the reference swapper
equally assumes a ``DeepSpeedCPUAdam``-style optimizer whose state is
two moments per parameter); the engine falls back to device-resident
state, with a warning, for anything else.
"""
from __future__ import annotations

import atexit
import hashlib
import os
import re
import shutil
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True        # exists, owned by someone else — leave it alone
    return True


def _swap_dir_name() -> str:
    # host+pid scoped: the liveness probe in _prune_stale_swap_dirs is
    # os.kill, which only means anything for OUR host's pids — on a mount
    # shared across hosts, a bare-pid name would let host B rmtree host A's
    # live swap dir just because A's pid happens to be unused on B
    import socket

    return f"zero_stage_nvme_opt.{socket.gethostname()}.{os.getpid()}"


def _prune_stale_swap_dirs(root: str) -> None:
    """Best-effort removal of this host's ``zero_stage_nvme_opt.<host>.<pid>``
    dirs whose owning process is dead (crashed/killed runs never reach
    teardown).  Other hosts' dirs are never touched (their pids are
    unknowable here); pid recycling can keep a stale dir alive — harmless,
    it is reclaimed once that pid dies."""
    import socket

    host = re.escape(socket.gethostname())
    try:
        entries = os.listdir(root)
    except OSError:
        return
    for name in entries:
        m = re.fullmatch(rf"zero_stage_nvme_opt\.{host}\.(\d+)", name)
        if not m or _pid_alive(int(m.group(1))):
            continue
        path = os.path.join(root, name)
        logger.info(f"pruning stale NVMe swap dir {path}")
        shutil.rmtree(path, ignore_errors=True)


def _close_weak(ref) -> None:
    swapper = ref()
    if swapper is not None:
        swapper.close()


def _norm_index(index, shape) -> tuple:
    """Normalize a shard's ``.index`` (tuple of slices) to a hashable
    ((start, stop), ...) key."""
    out = []
    for s, dim in zip(index, shape):
        if isinstance(s, slice):
            out.append((int(s.start or 0),
                        int(dim if s.stop is None else s.stop)))
        else:
            out.append((int(s), int(s) + 1))
    return tuple(out)


def _idx_tag(idx_norm: tuple) -> str:
    return hashlib.sha1(repr(idx_norm).encode()).hexdigest()[:8]


def _unique_shards(leaf) -> dict:
    """{normalized index -> one representative shard} over this process's
    addressable shards (replicated leaves repeat the same index on every
    local device — IO happens once per distinct slice)."""
    seen = {}
    for sh in leaf.addressable_shards:
        key = _norm_index(sh.index, leaf.shape)
        seen.setdefault(key, sh)
    return seen


def _to_device_space(x):
    """Move a pinned_host-resident array into device memory (leaf-wise —
    the swap loop's streaming granularity); anything else passes
    through."""
    sh = getattr(x, "sharding", None)
    if sh is not None and getattr(sh, "memory_kind", None) == "pinned_host":
        return jax.device_put(x, sh.with_memory_kind("device"))
    return x


def _float_leaf(x) -> bool:
    return jnp.issubdtype(np.asarray(x).dtype if not hasattr(x, "dtype")
                          else x.dtype, jnp.floating)


@partial(jax.jit, donate_argnums=(2, 3))
def _adam_update(p, g, m, v, count, lr, gscale, b1, b2, eps, wd, adam_w):
    """One leaf's AdamW update (reference ``csrc/adam`` kernel math /
    ``optax.scale_by_adam`` + decoupled decay).  ``gscale`` folds the
    1/(loss_scale*gas) unscale and the clip coefficient; ``adam_w``
    selects decoupled (True) vs L2 (folded into the gradient) decay."""
    g = g.astype(jnp.float32) * gscale
    g = jnp.where(adam_w, g, g + wd * p)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * jnp.square(g)
    m_hat = m / (1.0 - b1 ** count)
    v_hat = v / (1.0 - b2 ** count)
    u = m_hat / (jnp.sqrt(v_hat) + eps)
    u = jnp.where(adam_w, u + wd * p, u)
    p_new = (p - lr * u).astype(p.dtype)
    return p_new, m, v


class NvmeOptimizerSwapper:
    """Adam moments on NVMe, streamed through the device per step.

    One file per parameter leaf holding ``[m; v]`` contiguously in the
    master dtype; files are created lazily on the first successful step
    (zero-init moments never touch the disk).
    """

    def __init__(self, swap_dir: str, params: Any, *,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adam_w_mode: bool = True,
                 aio_block_size: int = 1 << 20,
                 aio_thread_count: int = 8,
                 aio_queue_depth: int = 64,
                 aio_use_odirect: bool = False):
        from deepspeed_tpu.io.aio import aio_handle

        # pid-scoped: two jobs pointing at the same NVMe mount must not
        # interleave moment files (swap state is transient — a resumed run
        # re-seeds its fresh dir from the checkpoint's nvme_optimizer/).
        # Swap state is worthless once its owning process is gone, so
        # (a) prune sibling dirs whose pids are dead before claiming ours
        # and (b) remove our own dir at exit — without this, long-lived
        # mounts accumulate dead 2x-fp32 moment sets until disk exhaustion.
        _prune_stale_swap_dirs(swap_dir)
        self.swap_dir = os.path.join(swap_dir, _swap_dir_name())
        os.makedirs(self.swap_dir, exist_ok=True)
        # weakref: an atexit handler holding `self` would pin every swapper
        # (and its native AIO thread pool) for process lifetime even after
        # its engine is dropped
        import weakref

        self._atexit = partial(_close_weak, weakref.ref(self))
        atexit.register(self._atexit)
        self._pending: list = []
        self._restored = False              # a load_from() succeeded
        self._reshard_warned = False
        self.handle = aio_handle(block_size=aio_block_size,
                                 thread_count=aio_thread_count,
                                 queue_depth=aio_queue_depth,
                                 use_odirect=aio_use_odirect)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.wd = float(weight_decay)
        self.adam_w_mode = bool(adam_w_mode)
        self.count = 0                      # successful (non-overflow) steps
        # (leaf key, shard index tag) pairs with moments on disk — THIS
        # process's shards only; other processes track their own
        self._initialized: set = set()
        # leaf registry: key -> (file basename, full shape, np dtype)
        self._meta: Dict[str, Tuple[str, tuple, np.dtype]] = {}
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        from deepspeed_tpu.checkpoint.sharded import path_str

        total = 0
        for kp, leaf in flat:
            if not _float_leaf(leaf):
                continue
            key = path_str(kp)
            # moments are ALWAYS fp32 on disk regardless of the param
            # (master) dtype — the update math promotes to fp32, and
            # sizing the layout by a bf16 param dtype would interleave
            # the m/v byte ranges
            dt = np.dtype(np.float32)
            # hash suffix keeps the name→file map injective ("/"→"__" alone
            # would collide for module names containing literal "__")
            digest = hashlib.sha1(key.encode()).hexdigest()[:8]
            base = os.path.join(
                self.swap_dir, f"{key.replace('/', '__')}-{digest}")
            self._meta[key] = (base, tuple(leaf.shape), dt)
            total += 2 * int(np.prod(leaf.shape)) * dt.itemsize
        log_dist(f"NVMe optimizer swap: {len(self._meta)} leaves, "
                 f"{total / 1e9:.2f} GB of moments (full tree) at "
                 f"{self.swap_dir}; this process swaps its addressable "
                 "shards", ranks=[0])

    # -- per-step IO ----------------------------------------------------

    # Moment files are PER ADDRESSABLE SHARD: ``<leaf>.<index-tag>.bin``.
    # Each process reads/writes only the slices its devices own, which is
    # what lifts the old single-controller restriction — a multi-host job
    # swaps its local ZeRO shards and never materializes a full leaf
    # (reference partitioned_optimizer_swapper semantics: every rank swaps
    # its own partition).

    def _shard_fname(self, key: str, tag: str) -> str:
        return f"{self._meta[key][0]}.{tag}.bin"

    def start_read(self, key: str, leaf) -> Dict[tuple, Optional[tuple]]:
        """Begin async moment reads for every distinct local shard of
        ``leaf``; entries are None where moments are zero-init."""
        dt = self._meta[key][2]
        out: Dict[tuple, Optional[tuple]] = {}
        for idx, sh in _unique_shards(leaf).items():
            tag = _idx_tag(idx)
            if (key, tag) not in self._initialized:
                if self._restored and not self._reshard_warned:
                    # shard tags are topology-keyed: a resumed run on a
                    # DIFFERENT process/device layout cannot match the
                    # saved moment files — moments restart zero.  (The
                    # params themselves reshard fine via the checkpoint
                    # store; only NVMe-swapped moments are layout-bound —
                    # resuming an NVMe-swap run on a new topology should
                    # go through a device-resident optimizer checkpoint.)
                    self._reshard_warned = True
                    logger.warning(
                        f"NVMe swap: restored moment set has no shard "
                        f"for {key!r} under the CURRENT sharding — the "
                        "topology changed since save; affected moments "
                        "restart from zero")
                out[idx] = None
                continue
            shp = tuple(sh.data.shape)
            nbytes = int(np.prod(shp)) * dt.itemsize
            m = np.empty(shp, dt)
            v = np.empty(shp, dt)
            fname = self._shard_fname(key, tag)
            out[idx] = (self.handle.async_pread(m, fname, 0),
                        self.handle.async_pread(v, fname, nbytes), m, v)
        return out

    def finish_read(self, key: str, leaf, started) -> Tuple[Any, Any]:
        """Join the shard reads and assemble GLOBAL moment arrays with the
        param leaf's sharding (each process contributes its local
        shards)."""
        dt = self._meta[key][2]
        vals: Dict[tuple, Tuple[np.ndarray, np.ndarray]] = {}
        for idx, st in started.items():
            if st is None:
                shp = tuple(b - a for a, b in idx)
                vals[idx] = (np.zeros(shp, dt), np.zeros(shp, dt))
            else:
                op_m, op_v, m, v = st
                self.handle.wait(op_m)
                self.handle.wait(op_v)
                vals[idx] = (m, v)
        shards = leaf.addressable_shards
        m_parts = [jax.device_put(vals[_norm_index(s.index, leaf.shape)][0],
                                  s.device) for s in shards]
        v_parts = [jax.device_put(vals[_norm_index(s.index, leaf.shape)][1],
                                  s.device) for s in shards]
        spec = jax.sharding.NamedSharding(
            leaf.sharding.mesh, leaf.sharding.spec) \
            if hasattr(leaf.sharding, "spec") else leaf.sharding
        m_dev = jax.make_array_from_single_device_arrays(
            leaf.shape, spec, m_parts)
        v_dev = jax.make_array_from_single_device_arrays(
            leaf.shape, spec, v_parts)
        return m_dev, v_dev

    def write(self, key: str, m_new, v_new) -> None:
        """Write this process's shards of the updated moments."""
        dt = self._meta[key][2]
        from deepspeed_tpu.io.aio import _pretruncate

        v_shards = _unique_shards(v_new)
        for idx, m_sh in _unique_shards(m_new).items():
            tag = _idx_tag(idx)
            fname = self._shard_fname(key, tag)
            m_np = np.ascontiguousarray(np.asarray(m_sh.data), dtype=dt)
            v_np = np.ascontiguousarray(np.asarray(v_shards[idx].data),
                                        dtype=dt)
            _pretruncate(fname, 2 * m_np.nbytes, exact=False)
            self._pending.append(self.handle.async_pwrite(
                m_np, fname, 0, _truncate=False))
            self._pending.append(self.handle.async_pwrite(
                v_np, fname, m_np.nbytes, _truncate=False))
            self._initialized.add((key, tag))

    def drain(self) -> None:
        """Wait EVERY pending write (even after one fails — a raised
        ``wait`` means that op finished; abandoning the rest would leave
        live IO racing later writes to the same files), then re-raise the
        first failure."""
        first_err = None
        try:
            for op in self._pending:
                try:
                    self.handle.wait(op)
                except Exception as e:       # op completed (failed); keep going
                    first_err = first_err or e
        finally:
            self._pending = []
        if first_err is not None:
            raise first_err

    def close(self) -> None:
        """Drain in-flight IO and delete the swap dir (moments are
        transient — resumable state lives in the checkpoint's
        ``nvme_optimizer/``, not here).  Idempotent; registered atexit
        (via weakref) and safe to call from engine teardown."""
        try:
            self.drain()
        except Exception:
            pass
        shutil.rmtree(self.swap_dir, ignore_errors=True)
        try:
            atexit.unregister(self._atexit)
        except Exception:
            pass

    # -- the step --------------------------------------------------------

    def apply(self, params: Any, grads: Any, *, lr, gscale) -> Any:
        """Update every float leaf in ``params`` against ``grads``;
        returns the new params tree.  Moments stream NVMe→HBM→NVMe with
        the next leaf's read overlapping the current leaf's update.

        A failure mid-loop leaves on-disk moments for already-processed
        leaves one step ahead of the abandoned params tree, so the swap
        state is INVALID after an exception escapes: in-flight IO is
        drained (finally) and ``_initialized`` is cleared, forcing
        zero-init moments (or a checkpoint reload) rather than silently
        mixing half-advanced state into a retried step."""
        from deepspeed_tpu.checkpoint.sharded import path_str

        self.count += 1
        count = jnp.asarray(self.count, jnp.float32)
        lr = jnp.asarray(lr, jnp.float32)
        gscale = jnp.asarray(gscale, jnp.float32)
        flat_p = jax.tree_util.tree_flatten_with_path(params)
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        keys = [path_str(kp) for kp, _ in flat_p[0]]
        leaves = [leaf for _, leaf in flat_p[0]]
        todo = [i for i, leaf in enumerate(leaves) if _float_leaf(leaf)]

        started = {}
        ok = False
        try:
            if todo:
                i0 = todo[0]
                started[i0] = self.start_read(keys[i0], leaves[i0])
            new_leaves = list(leaves)
            for pos, i in enumerate(todo):
                if pos + 1 < len(todo):                 # prefetch next leaf
                    nxt = todo[pos + 1]
                    started[nxt] = self.start_read(keys[nxt], leaves[nxt])
                p, g = leaves[i], flat_g[i]
                # host-offloaded params/grads (ZeRO-Infinity composition)
                # stream through DEVICE memory one leaf at a time — jit
                # math can't mix host- and device-space operands
                p = _to_device_space(p)
                g = _to_device_space(g)
                m_dev, v_dev = self.finish_read(keys[i], p,
                                                started.pop(i))
                p_new, m_new, v_new = _adam_update(
                    p, g, m_dev, v_dev, count, lr, gscale,
                    self.b1, self.b2, self.eps, self.wd, self.adam_w_mode)
                if hasattr(p, "sharding"):
                    # keep the param's placement (incl. pinned_host when
                    # offload_param=cpu composes with the NVMe tier) — the jit
                    # output lands in default device memory otherwise
                    p_new = jax.device_put(p_new, p.sharding)
                new_leaves[i] = p_new
                self.write(keys[i], m_new, v_new)
            ok = True
        finally:
            # drain whatever was issued — leaked in-flight ops would race a
            # subsequent apply()/close() over the same files.  Cleanup waits
            # themselves can raise (that IS the failure mode being handled),
            # so every step is individually guarded: the `if not ok`
            # invalidation must run no matter what.
            for per_shard in started.values():
                for st in per_shard.values():
                    if st is None:
                        continue
                    for op in (st[0], st[1]):
                        try:
                            self.handle.wait(op)
                        except Exception:
                            pass             # op finished (failed read)
            drain_err = None
            try:
                self.drain()
            except Exception as e:           # a failed write corrupts a leaf
                drain_err = e
            if not ok or drain_err is not None:
                logger.error(
                    "NVMe optimizer apply() failed mid-stream; on-disk "
                    "moments are ahead of the params tree — invalidating "
                    "swap state (moments restart zero-init; reload the "
                    "checkpoint to recover real state)")
                self.count -= 1
                self._initialized.clear()
            if ok and drain_err is not None:
                raise drain_err
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), new_leaves)

    # -- checkpoint integration ------------------------------------------

    def save_to(self, ckpt_dir: str) -> None:
        """Copy the moment files into ``ckpt_dir`` (they already live on
        disk — checkpointing the swapped state is a file copy, the same
        trick the reference plays when NVMe-offloaded state is checkpointed
        alongside, ``engine.py:3277``)."""
        out = os.path.join(ckpt_dir, "nvme_optimizer")
        os.makedirs(out, exist_ok=True)
        self.drain()
        for key, tag in self._initialized:
            fname = self._shard_fname(key, tag)
            dst = os.path.join(out, os.path.basename(fname))
            # replicated leaves carry the same full-extent tag in every
            # process; copy via a per-process temp + atomic rename so
            # concurrent multi-host saves never interleave writes to one
            # destination path (fragile on e.g. NFS)
            tmp = f"{dst}.tmp.p{jax.process_index()}"
            shutil.copy2(fname, tmp)
            os.replace(tmp, dst)
        # one meta file per process: each process's shard set is disjoint
        # (multi-host swap — reference rank-local partition semantics)
        meta_name = f"swap_meta.p{jax.process_index()}.json"
        with open(os.path.join(out, meta_name), "w") as f:
            import json

            json.dump({"count": self.count,
                       "initialized": sorted(list(t)
                                             for t in self._initialized),
                       "adam_w_mode": self.adam_w_mode,
                       "betas": [self.b1, self.b2], "eps": self.eps,
                       "weight_decay": self.wd}, f)

    def _load_legacy(self, src: str, meta_f: str) -> bool:
        """Restore a pre-shard-format checkpoint (``swap_meta.json`` with
        whole-leaf entries and whole-leaf moment files).  The old writer
        was single-controller and always dumped FULL arrays, so each old
        file maps onto the full-extent shard tag; layouts that shard a
        leaf won't match and fall back to zero-init with the reshard
        warning."""
        import json

        with open(meta_f) as f:
            meta = json.load(f)
        self.count = int(meta["count"])
        self._initialized = set()
        for key in meta["initialized"]:
            if key not in self._meta:
                logger.warning(f"swapped state for unknown param {key!r} "
                               "ignored")
                continue
            base, shape, _ = self._meta[key]
            tag = _idx_tag(tuple((0, d) for d in shape))
            old_name = os.path.basename(base) + ".bin"
            old_path = os.path.join(src, old_name)
            if not os.path.exists(old_path):
                logger.warning(f"legacy moment file {old_name} missing")
                continue
            shutil.copy2(old_path, self._shard_fname(key, tag))
            self._initialized.add((key, tag))
        self._restored = True
        logger.info(f"migrated legacy NVMe swap meta ({len(self._initialized)} "
                    "whole-leaf moment files)")
        return True

    def load_from(self, ckpt_dir: str) -> bool:
        """Restore moment files saved by :meth:`save_to`; False when the
        checkpoint holds no swapped state (fresh moments)."""
        import json

        src = os.path.join(ckpt_dir, "nvme_optimizer")
        meta_f = os.path.join(
            src, f"swap_meta.p{jax.process_index()}.json")
        if not os.path.exists(meta_f):
            legacy = os.path.join(src, "swap_meta.json")
            if os.path.exists(legacy) and jax.process_index() == 0:
                return self._load_legacy(src, legacy)
            logger.warning("checkpoint has no NVMe-swapped optimizer state; "
                           "moments start fresh")
            return False
        with open(meta_f) as f:
            meta = json.load(f)
        saved = (tuple(meta.get("betas", (self.b1, self.b2))),
                 meta.get("eps", self.eps),
                 meta.get("weight_decay", self.wd),
                 meta.get("adam_w_mode", self.adam_w_mode))
        live = ((self.b1, self.b2), self.eps, self.wd, self.adam_w_mode)
        if saved != live:
            logger.warning(
                f"NVMe-swapped moments were produced with (betas, eps, wd, "
                f"adam_w_mode)={saved} but the live optimizer uses {live}; "
                "resuming applies the NEW coefficients to the old moments")
        self.count = int(meta["count"])
        self._initialized = set()
        for entry in meta["initialized"]:
            key, tag = entry
            if key not in self._meta:
                logger.warning(f"swapped state for unknown param {key!r} "
                               "ignored")
                continue
            fname = self._shard_fname(key, tag)
            shutil.copy2(os.path.join(src, os.path.basename(fname)), fname)
            self._initialized.add((key, tag))
        self._restored = True
        return True
