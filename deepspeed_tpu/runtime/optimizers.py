"""Optimizer factory.

Re-creation of the reference's ``_configure_basic_optimizer``
(``runtime/engine.py:1402``): the same config names (Adam, AdamW, FusedAdam,
Adagrad, Lamb, Lion, SGD, OneBitAdam, ...) resolve to optax gradient
transforms.  Learning rate is intentionally NOT baked into the transform —
the engine computes lr host-side from the schedule each step and applies
``p - lr * update`` inside the jitted step, so schedule changes never
retrace.

The reference's FusedAdam/CPUAdam CUDA/AVX kernels (``csrc/adam``) map to a
Pallas fused-optimizer kernel (``deepspeed_tpu.ops.fused_adam``) that the
engine substitutes for the optax path on TPU when
``optimizer.params.fused=true`` — same math, one kernel per param bucket.
1-bit optimizers (OneBitAdam/OneBitLamb/ZeroOneAdam): this builder returns
the uncompressed base math; the engine swaps in the error-feedback
compressed-momentum transforms (``runtime/onebit.py`` +
``comm/compressed.py``) when the topology is eligible (ZeRO stage 0, pure
DP — the reference's own restriction).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import optax

from deepspeed_tpu.utils.logging import logger

ADAM_LIKE = ("adam", "adamw", "fusedadam", "onebitadam", "zerooneadam")


def _common(params: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "lr": params.get("lr", 1e-3),
        "weight_decay": params.get("weight_decay", 0.0),
    }


def is_fused_optimizer(name: Optional[str], params: Dict[str, Any]) -> bool:
    """True when this (name, params) resolves to the Pallas fused kernels.

    Unlike the reference — where FusedAdam's CUDA multi-tensor kernel beats
    torch's unfused loop — XLA already fuses the optax chain into one
    elementwise kernel per leaf, and measured on v5e the Pallas path's
    tile/pad copies make it *slower*.  So "FusedAdam" configs get the
    XLA-fused optax math by default (same update), and the Pallas kernels
    are explicit opt-in via ``params.fused=true`` (they remain the building
    block for the qgZ/offload paths where custom fusion does pay)."""
    name = (name or "adamw").lower()
    return bool(dict(params or {}).get("fused", False)) and name in (
        "adam", "adamw", "fusedadam", "onebitadam", "zerooneadam", "lion",
        "fusedlion")


def build_optimizer(name: Optional[str], params: Dict[str, Any]
                    ) -> Tuple[optax.GradientTransformation, float]:
    """Return (lr-less transform, base_lr).

    The transform produces the raw update direction ``u``; the engine applies
    ``p_new = p - lr * u``.
    """
    name = (name or "adamw").lower()
    p = dict(params or {})
    base_lr = float(p.get("lr", 1e-3))
    betas = tuple(p.get("betas", (0.9, 0.999)))
    eps = float(p.get("eps", 1e-8))
    wd = float(p.get("weight_decay", 0.0))

    # 1-bit family: this builder returns the uncompressed base transform;
    # the ENGINE swaps in the compressed-momentum transform
    # (runtime/onebit.py) when the topology is eligible (stage 0, pure DP)
    # and logs which path is active — see DeepSpeedEngine._resolve_onebit.

    # fused Pallas kernels (csrc/adam, csrc/lion equivalents). Opt-in:
    # "FusedAdam"/"FusedLion" type or fused=true. The kernel has no GSPMD
    # partitioning rule, so the engine runs it inside shard_map over the
    # ZeRO moment layout (each device updates its own shard — the
    # stage_1_and_2.py step semantics). fused=false always opts out.
    fused = is_fused_optimizer(name, p)

    if name in ("adam", "adamw", "fusedadam", "onebitadam", "zerooneadam"):
        # adam_w_mode (reference FusedAdam flag): decoupled decay unless
        # explicitly plain Adam with adam_w_mode=False
        adam_w_mode = bool(p.get("adam_w_mode", name != "adam"))
        if fused:
            from deepspeed_tpu.ops.fused_adam import scale_by_fused_adam

            tx = scale_by_fused_adam(b1=betas[0], b2=betas[1], eps=eps,
                                     weight_decay=wd,
                                     adam_w_mode=adam_w_mode)
            return tx, base_lr
        chain = [optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps)]
        if wd:
            # decoupled decay; true L2 mode (decay folded into grads before
            # the moment update) exists only in the fused kernel — documented
            # divergence of the optax fallback
            chain.append(optax.add_decayed_weights(wd))
        tx = optax.chain(*chain)
    elif name in ("lamb", "onebitlamb"):
        # optax.lamb includes lr; rebuild lr-less: adam scaling + trust ratio
        chain = [optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps)]
        if wd:
            chain.append(optax.add_decayed_weights(wd))
        chain.append(optax.scale_by_trust_ratio())
        tx = optax.chain(*chain)
    elif name in ("lion", "fusedlion"):
        b1, b2 = tuple(p.get("betas", (0.9, 0.99)))
        if fused:
            from deepspeed_tpu.ops.fused_adam import scale_by_fused_lion

            return scale_by_fused_lion(b1=b1, b2=b2, weight_decay=wd), base_lr
        chain = [optax.scale_by_lion(b1=b1, b2=b2)]
        if wd:
            chain.append(optax.add_decayed_weights(wd))
        tx = optax.chain(*chain)
    elif name == "adagrad":
        chain = [optax.scale_by_rss(initial_accumulator_value=p.get(
            "initial_accumulator_value", 0.1), eps=eps)]
        if wd:
            chain.append(optax.add_decayed_weights(wd))
        tx = optax.chain(*chain)
    elif name == "sgd":
        momentum = float(p.get("momentum", 0.0))
        chain = []
        if momentum:
            chain.append(optax.trace(decay=momentum,
                                     nesterov=bool(p.get("nesterov", False))))
        if wd:
            chain.append(optax.add_decayed_weights(wd))
        tx = optax.chain(*chain) if chain else optax.identity()
    elif name in ("muadam", "muadamw", "musgd"):
        # muP optimizers (reference engine.py:1479 MuAdam/MuAdamW/MuSGD):
        # base optimizer + per-leaf lr multipliers from the base-model
        # shapes (runtime/mup.py).  ``params.base_shapes`` is the proxy
        # model's param-shape tree (what mup.set_base_shapes records).
        from deepspeed_tpu.runtime.mup import scale_by_mup

        base_shapes = p.get("base_shapes")
        if base_shapes is None:
            raise ValueError(
                f"{name} requires optimizer.params.base_shapes — the "
                "param-shape tree of the BASE (narrow) model, e.g. "
                "jax.tree_util.tree_map(lambda l: l.shape, "
                "base_model_params)")
        # decay chains AFTER the muP scaling: the multipliers apply to
        # the gradient-descent direction only, keeping the effective
        # decoupled decay at lr*wd for every width (the mup package's
        # MuAdamW scales wd by width_mult for exactly this invariance)
        if name == "musgd":
            momentum = float(p.get("momentum", 0.0))
            chain = []
            if momentum:
                chain.append(optax.trace(
                    decay=momentum, nesterov=bool(p.get("nesterov",
                                                        False))))
            chain.append(scale_by_mup(base_shapes, rule="sgd"))
            if wd:
                chain.append(optax.add_decayed_weights(wd))
        else:
            # decoupled decay like the adam branch above (true L2 mode
            # exists only in the fused kernel — same documented
            # divergence)
            chain = [optax.scale_by_adam(b1=betas[0], b2=betas[1],
                                         eps=eps),
                     scale_by_mup(base_shapes, rule="adam")]
            if wd:
                chain.append(optax.add_decayed_weights(wd))
        tx = optax.chain(*chain)
    else:
        raise ValueError(f"Unknown optimizer type {name!r}")
    return tx, base_lr
