"""The training engine.

TPU-native re-design of ``deepspeed/runtime/engine.py:184``
(``DeepSpeedEngine``) and ``deepspeed.initialize``
(``deepspeed/__init__.py:69``).  The reference wraps an ``nn.Module`` and
intercepts ``forward/backward/step`` with hooks; here the engine owns ONE
jitted ``train_step(state, batch, lr)`` that fuses forward, backward,
gradient accumulation (a ``lax.scan`` over micro-batches), ZeRO-sharded
update, loss scaling, clipping, and overflow skip — the whole of SURVEY
§3.2's call stack compiled into a single XLA program per shape.

The imperative ``forward()/backward()/step()`` triple is kept for API
parity (documented divergence: ``train_batch`` is the fast path; the
imperative mode runs forward twice — once for the returned loss, once
inside value_and_grad).
"""
from __future__ import annotations

import os
from functools import partial
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat

import deepspeed_tpu.comm as dist
from deepspeed_tpu.config import DeepSpeedConfig, load_config
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.resilience.distributed import CollectiveTimeout
from deepspeed_tpu.resilience.guards import SwapCorruptionError
from deepspeed_tpu.runtime import precision as prec
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader, shard_batch)
from deepspeed_tpu.runtime.lr_schedules import LRScheduler, get_schedule_fn
from deepspeed_tpu.runtime.optimizers import (build_optimizer,
                                              is_fused_optimizer)
from deepspeed_tpu.runtime.train_state import TrainState
from deepspeed_tpu.runtime.zero import ZeroShardingPlan, constrain_tree
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (BACKWARD_GLOBAL_TIMER,
                                       FORWARD_GLOBAL_TIMER,
                                       STEP_GLOBAL_TIMER,
                                       SynchronizedWallClockTimer,
                                       ThroughputTimer)

LossFn = Callable[[Any, Any, jax.Array], jax.Array]


def initialize(args=None,
               model: Any = None,
               optimizer: Optional[str] = None,
               model_parameters: Any = None,
               training_data: Any = None,
               lr_scheduler: Any = None,
               topology: Optional[MeshTopology] = None,
               dist_init_required: Optional[bool] = None,
               config: Any = None,
               config_params: Any = None,
               example_batch: Any = None,
               rng: Optional[jax.Array] = None,
               mpu: Any = None,
               engine_cls: Any = None,
               engine_kwargs: Optional[Dict] = None):
    """Create a training engine (reference ``deepspeed.initialize``,
    ``deepspeed/__init__.py:69``; same return arity).

    ``model`` is either
    - a flax ``nn.Module`` whose ``__call__(batch)`` returns the scalar
      loss (needs ``example_batch`` for init), or
    - a loss function ``loss_fn(params, batch, rng) -> scalar`` with the
      params pytree passed via ``model_parameters``.

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    dist.init_distributed()
    if topology is None:
        topology = dist.get_topology()
    else:
        dist.set_topology(topology)

    # batch accounting: samples split over data x expert ranks only — seq
    # ranks hold the same samples and split the sequence dim (Ulysses input
    # contract), so sp does NOT divide the batch
    ds_config = load_config(
        config if config is not None else config_params,
        dp_world_size=topology.data_parallel_size *
        topology.expert_parallel_size)

    # hpZ (ZeRO++): rebuild the mesh with the data axis split into
    # data x data_sub so stage-3 params can shard node-locally
    hpz = ds_config.zero_optimization.zero_hpz_partition_size
    if (hpz > 1 and ds_config.zero_optimization.stage >= 3 and
            topology.hpz_partition_size != hpz):
        topology = MeshTopology(
            dp=topology.data_parallel_size,
            tp=topology.tensor_parallel_size,
            pp=topology.pipe_parallel_size,
            sp=topology.sequence_parallel_size,
            ep=topology.expert_parallel_size,
            hpz=hpz,
            devices=list(topology.mesh.devices.flatten()))
        dist.set_topology(topology)
        log_dist(f"hpZ: split data axis -> {topology.describe()}", ranks=[0])

    cls = engine_cls or DeepSpeedEngine
    engine = cls(model=model,
                 model_parameters=model_parameters,
                 config=ds_config,
                 topology=topology,
                 optimizer_name=optimizer,
                 lr_scheduler=lr_scheduler,
                 training_data=training_data,
                 example_batch=example_batch,
                 rng=rng,
                 **(engine_kwargs or {}))
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


class DeviceBatch:
    """Marker wrapper for a batch already staged on device in the engine's
    [gas, micro, ...] layout (see ``DeepSpeedEngine.put_batch``)."""

    __slots__ = ("tree",)

    def __init__(self, tree):
        self.tree = tree


class OptimizerHandle:
    """Small view object returned as the ``optimizer`` element of the
    ``initialize`` tuple (the reference returns its wrapped optimizer; here
    state lives in the engine)."""

    def __init__(self, engine: "DeepSpeedEngine"):
        self._engine = engine

    @property
    def param_groups(self):
        return [{"lr": self._engine.get_lr()[0]}]

    def state_dict(self):
        return jax.device_get(self._engine.state.opt_state)

    def __repr__(self):  # pragma: no cover
        return f"OptimizerHandle({self._engine.optimizer_name})"


class DeepSpeedEngine:
    """Owns config, topology, sharded train state, and the compiled steps."""

    def __init__(self, model, model_parameters, config: DeepSpeedConfig,
                 topology: MeshTopology, optimizer_name: Optional[str] = None,
                 lr_scheduler=None, training_data=None, example_batch=None,
                 rng: Optional[jax.Array] = None):
        self.config = config
        self.topology = topology
        self.mesh = topology.mesh
        # resolve MoE dispatch_impl='auto' against THIS mesh no matter
        # when flax traces the layers (a trace issued before/without the
        # live topology would otherwise bake in the single-device choice)
        from deepspeed_tpu.moe.layer import pin_auto_dispatch

        pin_auto_dispatch(topology)
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0

        self.compute_dtype = prec.compute_dtype_from_config(config)
        self.dynamic_loss_scale = (config.fp16.enabled and
                                   config.fp16.loss_scale == 0)
        # master fp32 weights whenever compute dtype is lower precision
        self.master_weights = (config.fp16.enabled or
                               (config.bf16.enabled and config.bf16.master_weights))

        if rng is None:
            rng = jax.random.PRNGKey(config.seed)

        # -- resolve model -> (loss_fn, params) ---------------------------
        self.module = None
        self._init_rngs = None                 # set => deferred sharded init
        if hasattr(model, "init") and hasattr(model, "apply"):  # flax Module
            model = self._apply_activation_checkpointing_config(model)
            self.module = model
            assert example_batch is not None, \
                "flax-module path needs example_batch for init"
            init_rng, rng = jax.random.split(rng)
            if model_parameters is None:
                # zero.Init equivalent (partition_parameters.py:824): params
                # are born sharded.  Here: shapes only via eval_shape; the
                # real init runs later under jit with out_shardings from the
                # ZeRO plan, so no device or host ever materializes the
                # full unsharded model.
                self._init_rngs = {"params": init_rng, "dropout": init_rng}
                model_parameters = jax.eval_shape(
                    model.init, self._init_rngs, example_batch)

            def loss_fn(params, batch, step_rng):
                return model.apply(params, batch, rngs={"dropout": step_rng})
            self.loss_fn: LossFn = loss_fn
        elif callable(model):
            assert model_parameters is not None, \
                "loss-fn path needs model_parameters"
            self.loss_fn = model
        else:
            raise TypeError(f"Unsupported model type {type(model)}")

        # -- optimizer ----------------------------------------------------
        opt_cfg = config.optimizer
        self.optimizer_name = (optimizer_name or
                               (opt_cfg.type if opt_cfg else "adamw"))
        opt_params = dict(opt_cfg.params) if opt_cfg else {}
        self.tx, base_lr = build_optimizer(self.optimizer_name, opt_params)
        self._onebit_axes = self._resolve_onebit(topology, opt_params)

        # -- lr schedule --------------------------------------------------
        if lr_scheduler is None:
            sched_cfg = config.scheduler
            sched_fn = get_schedule_fn(
                sched_cfg.type if sched_cfg else None,
                dict(sched_cfg.params) if sched_cfg else {}, base_lr=base_lr)
            lr_scheduler = LRScheduler(sched_fn)
        self.lr_scheduler = lr_scheduler

        # -- tensor-parallel base specs (flax metadata or AutoTP) ---------
        from deepspeed_tpu.parallel import tensor_parallel as tp_lib

        self.base_specs = None
        params_boxed = tp_lib.has_partitioning(model_parameters)
        if params_boxed:
            self.base_specs = tp_lib.extract_partition_specs(
                model_parameters, self.mesh.axis_names)
            model_parameters = tp_lib.unbox_params(model_parameters)
        elif topology.tensor_parallel_size > 1:
            # AutoTP (module_inject/auto_tp.py equivalent): infer specs from
            # parameter names when the model carries no annotations
            self.base_specs = tp_lib.auto_tp_specs(
                model_parameters, topology.tensor_parallel_size)
            log_dist("AutoTP: inferred tensor-parallel sharding from "
                     "parameter names", ranks=[0])
        # pipeline-stage params: stage dim -> `pipe` axis (no-op otherwise)
        from deepspeed_tpu.parallel.pipeline import (apply_pipeline_specs,
                                                     validate_pipeline_layout)

        self.base_specs = apply_pipeline_specs(model_parameters,
                                               self.base_specs)
        validate_pipeline_layout(model_parameters, topology)

        # -- ZeRO sharding plan + state materialization -------------------
        zcfg = config.zero_optimization
        self.zero_stage = zcfg.stage
        self.plan = ZeroShardingPlan(
            topology, zcfg.stage,
            persistence_threshold=zcfg.stage3_param_persistence_threshold,
            hpz_partition_size=zcfg.zero_hpz_partition_size)

        master_dtype = jnp.float32 if self.master_weights else self.compute_dtype

        def to_master(x):
            return (x.astype(master_dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x)

        # -- ZeRO-Offload (swap_tensor/partitioned_*_swapper equivalents):
        # state placed in host memory via memory_kind="pinned_host"; XLA
        # streams it to the chip inside the step.  TPU-only: the CPU
        # backend cannot compile host-placement annotations.
        offl_o, offl_p = zcfg.offload_optimizer, zcfg.offload_param
        want_opt_off = bool(offl_o and offl_o.device == "cpu")
        # NVMe tier (ZeRO-Infinity, swap_tensor/partitioned_optimizer_
        # swapper.py): moments on local SSD, streamed through the device
        # per step by the native AIO engine.  Adam-family only (the
        # reference swapper equally assumes two-moment CPU-Adam state).
        # Multi-process capable: each process swaps only its addressable
        # ZeRO shards into per-shard files (reference rank-local
        # partition semantics).
        self.nvme_swapper = None
        want_opt_nvme = bool(offl_o and offl_o.device == "nvme")
        if want_opt_nvme:
            adam_family = (self.optimizer_name or "adamw").lower() in (
                "adam", "adamw", "fusedadam")
            if not adam_family or self._onebit_axes is not None:
                logger.warning(
                    "offload_optimizer.device=nvme needs an Adam-family "
                    "optimizer; keeping optimizer state in device memory")
                want_opt_nvme = False
            elif not offl_o.nvme_path:
                # a shared default path would let concurrent jobs clobber
                # each other's moment files (the reference swapper equally
                # requires nvme_path)
                raise ValueError(
                    "offload_optimizer.device=nvme requires "
                    "offload_optimizer.nvme_path")
        want_param_off = bool(offl_p and offl_p.device == "cpu" and
                              zcfg.stage >= 3)
        if offl_p and offl_p.device == "cpu" and zcfg.stage < 3:
            logger.warning(
                f"offload_param.device=cpu requires zero stage 3 (params "
                f"are not partitioned at stage {zcfg.stage}); IGNORED")
        host_mem_ok = self.mesh.devices.flat[0].platform != "cpu"
        if (want_opt_off or want_param_off) and not host_mem_ok:
            logger.warning(
                "offload to cpu requested but this backend cannot compile "
                "pinned_host placement; keeping state in device memory")
        self.offload_optimizer = want_opt_off and host_mem_ok
        self.offload_param = want_param_off and host_mem_ok

        def to_host(shardings):
            return jax.tree_util.tree_map(
                lambda s: s.with_memory_kind("pinned_host"), shardings)

        param_shardings = self.plan.param_shardings(model_parameters,
                                                    self.base_specs)
        # in-graph H2D fetch: host-resident operands must be explicitly
        # transferred before compute ops (XLA does not auto-stream them)
        self._fetch_params = lambda p: p
        self._fetch_opt = lambda o: o
        if self.offload_param:
            dev_shardings = param_shardings
            param_shardings = to_host(param_shardings)
            self._fetch_params = (
                lambda p, _s=dev_shardings: jax.device_put(p, _s))
            log_dist("ZeRO-Offload: params resident in host memory "
                     "(pinned_host)", ranks=[0])
        if self._init_rngs is not None:
            # deferred init: each device computes/receives only its shard
            def sharded_init(rngs, batch):
                p = model.init(rngs, batch)
                if params_boxed:
                    p = tp_lib.unbox_params(p)
                return jax.tree_util.tree_map(to_master, p)

            params = jax.jit(sharded_init, out_shardings=param_shardings)(
                self._init_rngs, example_batch)
        else:
            # user-provided params: already materialized; cast on host and
            # place leaf-by-leaf against the plan (no second full-tree copy)
            def put(x, s):
                x = np.asarray(x)
                if jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(master_dtype)
                return jax.device_put(x, s)

            params = jax.tree_util.tree_map(put, model_parameters,
                                            param_shardings)
        self._grad_spec_tree = self.plan.grad_specs(params, self.base_specs)

        # streamed host-moment tier: offload_optimizer=cpu TOGETHER with
        # offload_param=cpu is the "model far beyond HBM" configuration —
        # the fused single-program path materializes every gradient
        # before the first moment write there (measured 41G of HBM at
        # 7B), so Adam moments stream through the device bucket-by-bucket
        # from pinned host memory instead (reference CPU-Adam +
        # offload_optimizer semantics, zero/stage3.py)
        _p_cfg = dict(opt_cfg.params) if opt_cfg else {}
        _name = (self.optimizer_name or "adamw").lower()
        _adam_family = _name in ("adam", "adamw", "fusedadam")
        # mirror exactly what the device-resident transform the swapped
        # tiers replace would have done: the fused Pallas path honors
        # adam_w_mode (default: decoupled unless plain "Adam" —
        # optimizers.py:84), while the optax fallback always decouples
        # (documented divergence) regardless of the flag
        if is_fused_optimizer(_name, _p_cfg):
            _adam_w = bool(_p_cfg.get("adam_w_mode", _name != "adam"))
        else:
            _adam_w = True
        want_opt_stream = (self.offload_optimizer and self.offload_param
                           and _adam_family
                           and self._onebit_axes is None
                           and jax.process_count() == 1)
        if want_opt_nvme:
            from deepspeed_tpu.runtime.swap_tensor import NvmeOptimizerSwapper

            self.nvme_swapper = NvmeOptimizerSwapper(
                offl_o.nvme_path, params,
                betas=tuple(_p_cfg.get("betas", (0.9, 0.999))),
                eps=float(_p_cfg.get("eps", 1e-8)),
                weight_decay=float(_p_cfg.get("weight_decay", 0.0)),
                adam_w_mode=_adam_w,
                aio_block_size=config.aio.block_size,
                aio_thread_count=config.aio.thread_count,
                aio_queue_depth=config.aio.queue_depth,
                aio_use_odirect=config.aio.use_odirect,
                pipeline_read=offl_o.pipeline_read,
                pipeline_write=offl_o.pipeline_write,
                buffer_count=offl_o.buffer_count,
                sdc_verify=config.resilience.sdc.verify_on_read,
                sdc_checksum=config.resilience.sdc.checksum,
                sdc_max_reread=config.resilience.sdc.max_reread_retries)
            opt_state, opt_shardings, opt_specs = (), (), None
        elif want_opt_stream:
            from deepspeed_tpu.runtime.swap_tensor import HostMomentSwapper

            self.nvme_swapper = HostMomentSwapper(
                params,
                betas=tuple(_p_cfg.get("betas", (0.9, 0.999))),
                eps=float(_p_cfg.get("eps", 1e-8)),
                weight_decay=float(_p_cfg.get("weight_decay", 0.0)),
                adam_w_mode=_adam_w)
            opt_state, opt_shardings, opt_specs = (), (), None
        elif self._onebit_axes is not None:
            opt_state, opt_shardings = self._init_onebit_opt_state(params)
            opt_specs = None
        else:
            opt_shapes = jax.eval_shape(self.tx.init, params)
            opt_specs = self.plan.opt_state_specs(opt_shapes, self.base_specs)
            opt_shardings = self.plan.opt_state_shardings(opt_shapes,
                                                          self.base_specs)
        if self.offload_optimizer and self._onebit_axes is not None:
            logger.warning("offload_optimizer is not supported on the "
                           "1-bit compressed path; keeping state on device")
            self.offload_optimizer = False
        if self.offload_optimizer and self.nvme_swapper is None:
            dev_opt_shardings = opt_shardings
            opt_shardings = to_host(opt_shardings)
            self._fetch_opt = (
                lambda o, _s=dev_opt_shardings: jax.device_put(o, _s))
            log_dist("ZeRO-Offload: optimizer state resident in host "
                     "memory (pinned_host)", ranks=[0])
        if self._onebit_axes is None and self.nvme_swapper is None:
            opt_state = jax.jit(self.tx.init,
                                out_shardings=opt_shardings)(params)

        # Fused Pallas optimizers have no GSPMD partitioning rule; run the
        # update inside shard_map over the ZeRO moment layout so each device
        # updates only its own shard (stage_1_and_2.py step semantics: shard
        # update + all-gather of the result, which XLA inserts when the
        # engine applies p - lr*u against less-sharded params).
        self._tx_update = self.tx.update
        if self._onebit_axes is None and self.nvme_swapper is None and \
                is_fused_optimizer(
                self.optimizer_name, opt_cfg.params if opt_cfg else {}):
            moment_specs = self.plan.moment_specs(params, self.base_specs)
            self._tx_update = _shard_map_compat(
                self.tx.update, mesh=self.mesh,
                in_specs=(moment_specs, opt_specs, moment_specs),
                out_specs=(moment_specs, opt_specs),
                check_vma=False)

        scale_state = prec.init_loss_scale(config.fp16)
        self.state = TrainState(
            step=jnp.asarray(0, jnp.int32),
            params=params,
            opt_state=opt_state,
            scale=jax.device_put(scale_state),
            rng=rng,
            skipped_steps=jnp.asarray(0, jnp.int32))
        log_dist(self.plan.describe(params, self.base_specs), ranks=[0])

        self._state_shardings = TrainState(
            step=self._repl(), params=param_shardings,
            opt_state=opt_shardings,
            scale=jax.tree_util.tree_map(lambda _: self._repl(), scale_state),
            rng=self._repl(),
            skipped_steps=self._repl())

        # -- data ---------------------------------------------------------
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = RepeatingLoader(DeepSpeedDataLoader(
                training_data, batch_size=config.train_batch_size,
                seed=config.seed, drop_last=config.dataloader_drop_last,
                world_size=self.topology.world_size))
        self._data_iter = None

        # -- compiled steps (built lazily per batch structure) ------------
        self._train_step_fn = None
        self._eval_step_fn = None
        self._grad_step_fn = None
        self._nvme_grad_step_fn = None
        self._apply_step_fn = None
        self._pending_grads = None
        self._pending_loss = None
        self._profile_batch = None
        self._lr_cached_value = None
        self._lr_cached_dev = None

        # -- observability -------------------------------------------------
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size,
            steps_per_output=config.steps_per_print)
        self.monitor = None
        try:
            from deepspeed_tpu.monitor.monitor import MonitorMaster

            self.monitor = MonitorMaster(config.monitor_config)
        except Exception as e:
            logger.warning(f"monitor setup failed; metric logging disabled: {e}")
        dist.configure(config.comms_logger)

        # legacy curriculum learning (reference engine
        # curriculum_enabled_legacy path): seqlen difficulty truncates
        # token batches; difficulty_step quantizes compile shapes
        self.curriculum_scheduler = None
        if config.curriculum_learning.enabled:
            from deepspeed_tpu.data_pipeline import CurriculumScheduler

            if config.curriculum_learning.curriculum_type != "seqlen":
                raise ValueError(
                    "curriculum_learning.curriculum_type="
                    f"{config.curriculum_learning.curriculum_type!r}: the "
                    "engine-wired legacy path supports 'seqlen' (other "
                    "metrics go through deepspeed_tpu.data_pipeline."
                    "DeepSpeedDataSampler)")
            self.curriculum_scheduler = CurriculumScheduler(
                config.curriculum_learning.model_dump())
            log_dist("curriculum learning: seqlen "
                     f"{config.curriculum_learning.min_difficulty} -> "
                     f"{config.curriculum_learning.max_difficulty} "
                     f"({config.curriculum_learning.schedule_type})",
                     ranks=[0])

        # -- resilience guards (resilience/guards.py) ---------------------
        self._skip_guard = None
        # check_grad_finite extends the consecutive-skip abort to
        # bf16/fp32 runs (their non-finite sweep is built into the
        # train step when the knob is on); when both knobs are set the
        # tighter bound wins
        _guard_bounds = [b for b in (
            config.resilience.max_consecutive_skips,
            config.resilience.check_grad_finite) if b > 0]
        if _guard_bounds:
            from deepspeed_tpu.resilience import SkippedStepGuard

            self._skip_guard = SkippedStepGuard(min(_guard_bounds))
        self._preemption_prev_handlers = None
        self._preemption_save_dir = None
        self.preempted = False
        self.swap_corrupted = False
        # -- distributed health (resilience/distributed.py) ---------------
        self.comm_timed_out = False
        self._desync = None
        rc = config.resilience.comm
        if rc.collective_timeout_s > 0:
            from deepspeed_tpu.comm import watchdog as _cwd

            _cwd.configure(rc.collective_timeout_s)
            log_dist(f"collective watchdog armed: "
                     f"{rc.collective_timeout_s:.1f}s deadline", ranks=[0])
        if rc.desync_interval > 0:
            from deepspeed_tpu.resilience import DesyncDetector

            self._desync = DesyncDetector(rc.desync_interval,
                                          rc.desync_tolerance)

        self.optimizer = OptimizerHandle(self)
        log_dist(
            f"DeepSpeedEngine: zero_stage={self.zero_stage} "
            f"dtype={self.compute_dtype.__name__} "
            f"micro={config.train_micro_batch_size_per_gpu} "
            f"gas={config.gradient_accumulation_steps} "
            f"train_batch={config.train_batch_size}", ranks=[0])

    # ------------------------------------------------------------------

    def _resolve_onebit(self, topology, opt_params):
        """1-bit optimizer family routing (reference
        ``runtime/fp16/onebit/adam.py:14``): when eligible, swap ``self.tx``
        for the compressed-momentum transform and return the comm axes the
        shard_map train step runs over.  Eligibility mirrors the
        reference's restrictions — ZeRO stage 0 (OnebitAdam asserts
        non-ZeRO), pure DP (no tp/pp/sp/ep), no fp16 loss scaling — plus
        >1 data member (nothing to compress otherwise)."""
        name = self.optimizer_name.lower()
        if name not in ("onebitadam", "onebitlamb", "zerooneadam"):
            return None
        n_dp = topology.zero_partition_count()
        blockers = []
        if name == "zerooneadam":
            blockers.append("0/1 Adam's local-step phase holds per-member "
                            "params, incompatible with the replicated "
                            "engine state (use the transform standalone)")
        if self.config.zero_optimization.stage != 0:
            blockers.append(f"zero stage "
                            f"{self.config.zero_optimization.stage} != 0")
        for ax_attr, label in (("tensor_parallel_size", "tp"),
                               ("pipe_parallel_size", "pp")):
            if getattr(topology, ax_attr) > 1:
                blockers.append(f"{label} > 1")
        for ax in ("seq", "expert"):
            if topology.axis_size(ax) > 1:
                blockers.append(f"{ax} axis > 1")
        if self.config.fp16.enabled:
            blockers.append("fp16 dynamic loss scaling")
        if n_dp <= 1:
            blockers.append("single data-parallel member")
        if blockers:
            logger.warning(
                f"{self.optimizer_name}: compressed-communication path "
                f"disabled ({'; '.join(blockers)}); using the uncompressed "
                "base optimizer (same warmup-stage math, full-precision "
                "wire)")
            return None
        from deepspeed_tpu.parallel.topology import DATA_AXIS, HPZ_AXIS
        from deepspeed_tpu.runtime.onebit import (scale_by_onebit_adam,
                                                  scale_by_onebit_lamb)

        axes = tuple(a for a in (DATA_AXIS, HPZ_AXIS)
                     if topology.axis_size(a) > 1)
        betas = tuple(opt_params.get("betas", (0.9, 0.999)))
        kw = dict(b1=betas[0], b2=betas[1],
                  freeze_step=int(opt_params.get("freeze_step", 100000)),
                  weight_decay=float(opt_params.get("weight_decay", 0.0)),
                  group=axes)
        if name == "onebitlamb":
            self.tx = scale_by_onebit_lamb(
                eps=float(opt_params.get("eps", 1e-6)), **kw)
        else:
            self.tx = scale_by_onebit_adam(
                eps=float(opt_params.get("eps", 1e-8)), **kw)
        if self.config.gradient_clipping:
            logger.warning(
                f"{self.optimizer_name}: gradient_clipping is not supported "
                "on the compressed path (the reference raises for "
                "max_grad_norm); clipping is skipped")
        log_dist(f"{self.optimizer_name}: 1-bit compressed momentum "
                 f"all-reduce active over axes {axes} "
                 f"(freeze_step={kw['freeze_step']})", ranks=[0])
        return axes

    def _apply_activation_checkpointing_config(self, model):
        """Honor the ``activation_checkpointing`` JSON subtree (reference
        ``runtime/activation_checkpointing/checkpointing.py`` configure):
        when explicitly set, rebuild the model's dataclass config with the
        matching ``nn.remat`` policy so the knob actually drives remat."""
        import dataclasses

        if "activation_checkpointing" not in self.config.model_fields_set:
            return model
        acfg = self.config.activation_checkpointing
        if acfg.cpu_checkpointing or acfg.contiguous_memory_optimization:
            logger.warning(
                "activation_checkpointing: cpu_checkpointing / "
                "contiguous_memory_optimization are no-ops on TPU (XLA "
                "owns activation placement and memory layout)")
        # only an explicit policy (or partition_activations, whose TPU
        # equivalent is remat) changes remat behavior — other fields in the
        # block (profile, ...) must not silently enable checkpointing
        if ("policy" not in acfg.model_fields_set and
                not acfg.partition_activations):
            return model
        mc = getattr(model, "config", None)
        if not (dataclasses.is_dataclass(mc) and
                all(any(f.name == n for f in dataclasses.fields(mc))
                    for n in ("remat", "remat_policy"))):
            logger.warning(
                "activation_checkpointing set but the model carries no "
                "remat-capable dataclass config; knob has no effect")
            return model
        # config policy names -> (model remat_policy, remat on?)
        mapping = {"nothing_saveable": ("full", True),
                   "dots_saveable": ("dots_saveable", True),
                   "everything_saveable": ("none", False)}
        if acfg.policy not in mapping:
            raise ValueError(
                f"activation_checkpointing.policy={acfg.policy!r}: expected "
                f"one of {sorted(mapping)}")
        remat_policy, remat = mapping[acfg.policy]
        if (mc.remat, mc.remat_policy) == (remat, remat_policy):
            return model
        log_dist(f"activation_checkpointing: policy={acfg.policy} -> "
                 f"remat={remat} remat_policy={remat_policy}", ranks=[0])
        # clone preserves every other module field (a module may carry more
        # than its config)
        return model.clone(config=dataclasses.replace(
            mc, remat=remat, remat_policy=remat_policy))

    def _repl(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def gas(self) -> int:
        return self.config.gradient_accumulation_steps

    def get_lr(self):
        return self.lr_scheduler.get_lr()

    @property
    def loss_scale(self) -> float:
        return float(jax.device_get(self.state.scale.loss_scale))

    @property
    def skipped_steps(self) -> int:
        return int(jax.device_get(self.state.skipped_steps))

    # ------------------------------------------------------------------
    # Compiled step builders
    # ------------------------------------------------------------------

    def _init_onebit_opt_state(self, params):
        """Global layout for :class:`OnebitState`: moments replicated (stage
        0), error-feedback accumulators stored with a leading member axis
        sharded over the comm axes (each member owns exactly its own error
        — the reference keeps them as per-rank tensors)."""
        axes = self._onebit_axes
        n = int(np.prod([self.topology.axis_size(a) for a in axes]))
        shapes = jax.eval_shape(self.tx.init, params)
        err_sharding = NamedSharding(self.mesh, P(axes))
        shardings = jax.tree_util.tree_map(
            lambda _: self._repl(), shapes)._replace(
            worker_error=jax.tree_util.tree_map(
                lambda _: err_sharding, shapes.worker_error),
            server_error=jax.tree_util.tree_map(
                lambda _: err_sharding, shapes.server_error))

        def init_global(p):
            s = self.tx.init(p)
            return s._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda e: jnp.broadcast_to(e[None], (n,) + e.shape),
                    s.worker_error),
                server_error=jax.tree_util.tree_map(
                    lambda e: jnp.broadcast_to(e[None], (n,) + e.shape),
                    s.server_error))

        state = jax.jit(init_global, out_shardings=shardings)(params)
        return state, shardings

    def _build_onebit_train_step(self, gbatch):
        """shard_map train step for the 1-bit family: the data axes are
        MANUAL, so gradients stay member-local (no GSPMD psum in backward)
        and the only cross-member traffic is the transform's compressed
        momentum all-reduce — the reference ``OnebitAdam.step`` wire
        pattern, fused into the one compiled program."""
        axes = self._onebit_axes
        mesh = self.mesh
        loss_fn = self.loss_fn
        tx = self.tx
        gas = self.gas
        compute_dtype = self.compute_dtype

        def cast_params(p):
            return prec.cast_tree(p, compute_dtype)

        repl = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        state_specs = TrainState(
            step=P(), params=repl(self.state.params),
            opt_state=repl(self.state.opt_state)._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda _: P(axes), self.state.opt_state.worker_error),
                server_error=jax.tree_util.tree_map(
                    lambda _: P(axes), self.state.opt_state.server_error)),
            scale=repl(self.state.scale), rng=P(), skipped_steps=P())
        batch_specs = jax.tree_util.tree_map(
            lambda x: P(*((None, axes) + (None,) * (x.ndim - 2))), gbatch)
        metric_specs = {k: P() for k in ("loss", "grad_norm", "overflow",
                                         "loss_scale")}

        def member_step(state: TrainState, batch, lr):
            rng, new_rng = jax.random.split(state.rng)
            if len(axes) == 1:
                member = jax.lax.axis_index(axes[0])
            else:
                member = (jax.lax.axis_index(axes[0]) *
                          jax.lax.axis_size(axes[1]) +
                          jax.lax.axis_index(axes[1]))
            params = state.params

            def micro_grads(mb, idx):
                mrng = jax.random.fold_in(jax.random.fold_in(rng, idx),
                                          member)

                def local_loss(p):
                    return loss_fn(cast_params(p), mb, mrng).astype(
                        jnp.float32)

                loss, grads = jax.value_and_grad(local_loss)(params)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads)
                return grads, loss

            if gas == 1:
                grads, loss_sum = micro_grads(
                    jax.tree_util.tree_map(lambda x: x[0], batch), 0)
            else:
                def micro_step(carry, xs):
                    grads_acc, loss_acc = carry
                    mb, idx = xs
                    g, l = micro_grads(mb, idx)
                    return (jax.tree_util.tree_map(jnp.add, grads_acc, g),
                            loss_acc + l), None

                zero_grads = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)
                (grads, loss_sum), _ = jax.lax.scan(
                    micro_step, (zero_grads, jnp.asarray(0.0, jnp.float32)),
                    (batch, jnp.arange(gas)))
                grads = jax.tree_util.tree_map(lambda g: g / gas, grads)

            opt_in = state.opt_state._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda e: e[0], state.opt_state.worker_error),
                server_error=jax.tree_util.tree_map(
                    lambda e: e[0], state.opt_state.server_error))
            updates, new_opt = tx.update(grads, opt_in, params)
            new_params = jax.tree_util.tree_map(
                lambda p, u: (p - lr * u.astype(jnp.float32)).astype(p.dtype),
                params, updates)
            new_opt = new_opt._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda e: e[None], new_opt.worker_error),
                server_error=jax.tree_util.tree_map(
                    lambda e: e[None], new_opt.server_error))

            loss = jax.lax.pmean(loss_sum / gas, axes)
            # norm of the member-local gradient, RMS-averaged across members
            grad_norm = jnp.sqrt(jax.lax.pmean(
                prec.global_norm(grads) ** 2, axes))
            new_state = TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt,
                scale=state.scale, rng=new_rng,
                skipped_steps=state.skipped_steps)
            metrics = {"loss": loss, "grad_norm": grad_norm,
                       "overflow": jnp.asarray(False),
                       "loss_scale": state.scale.loss_scale}
            return new_state, metrics

        sharded = _shard_map_compat(
            member_step, mesh=mesh,
            in_specs=(state_specs, batch_specs, P()),
            out_specs=(state_specs, metric_specs), check_vma=False)
        metric_shardings = {k: self._repl() for k in metric_specs}
        return jax.jit(sharded,
                       in_shardings=(self._state_shardings, None, None),
                       out_shardings=(self._state_shardings,
                                      metric_shardings),
                       donate_argnums=(0,))

    def _build_train_step(self):
        plan = self.plan
        mesh = self.mesh
        loss_fn = self.loss_fn
        tx_update = self._tx_update
        gas = self.gas
        compute_dtype = self.compute_dtype
        clip = self.config.gradient_clipping
        fp16 = self.config.fp16
        dynamic = self.dynamic_loss_scale
        grad_specs = self._grad_spec_tree
        fetch_params = self._fetch_params
        fetch_opt = self._fetch_opt

        def cast_params(p):
            return prec.cast_tree(p, compute_dtype)

        # overflow scanning exists for fp16 loss-scaling; bf16/fp32 training
        # never skips steps (reference bf16_optimizer has no overflow path),
        # so skip the full-gradient inf/nan sweep there — unless
        # resilience.check_grad_finite folds it in (non-finite bf16/fp32
        # steps then skip, and N consecutive ones abort via the guard)
        check_overflow = (self.config.fp16.enabled
                          or self.config.resilience.check_grad_finite > 0)

        def train_step(state: TrainState, batch, lr):
            rng, new_rng = jax.random.split(state.rng)
            scale = state.scale.loss_scale
            # ZeRO-Offload: explicit H2D fetch of host-resident state
            live_params = fetch_params(state.params)
            live_opt = fetch_opt(state.opt_state)

            def micro_grads(mb, idx):
                mrng = jax.random.fold_in(rng, idx)

                def scaled_loss(p):
                    loss = loss_fn(cast_params(p), mb, mrng)
                    return (loss * scale.astype(loss.dtype)).astype(jnp.float32)

                loss_s, grads = jax.value_and_grad(scaled_loss)(live_params)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads)
                # ZeRO >= 2: keep accumulated grads in the sharded layout so
                # XLA reduce-scatters each micro-batch (stage_1_and_2.py
                # average_tensor hot loop equivalent)
                return constrain_tree(grads, grad_specs, mesh), loss_s

            if gas == 1:
                # fast path: no accumulation buffers, no scan
                grads, loss_sum = micro_grads(
                    jax.tree_util.tree_map(lambda x: x[0], batch), 0)
            else:
                def micro_step(carry, xs):
                    grads_acc, loss_acc = carry
                    mb, idx = xs
                    grads, loss_s = micro_grads(mb, idx)
                    grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc,
                                                       grads)
                    return (grads_acc, loss_acc + loss_s), None

                zero_grads = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), live_params)
                zero_grads = constrain_tree(zero_grads, grad_specs, mesh)
                idxs = jnp.arange(gas)
                (grads, loss_sum), _ = jax.lax.scan(
                    micro_step, (zero_grads, jnp.asarray(0.0, jnp.float32)),
                    (batch, idxs))

            # unscale (loss scale) and average (GAS); data-parallel averaging
            # already happened inside the mean loss over the global batch
            if check_overflow or gas > 1:  # loss was scaled / accumulated
                inv = 1.0 / (scale * gas)
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)

            grad_norm = prec.global_norm(grads)
            if clip and clip > 0:
                grads, _ = prec.clip_by_global_norm(grads, clip, grad_norm)

            if check_overflow:
                overflow = prec.has_inf_or_nan(grads)
                safe_grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(overflow, jnp.zeros_like(g), g), grads)
                updates, new_opt = tx_update(safe_grads, live_opt,
                                             live_params)
                new_params = jax.tree_util.tree_map(
                    lambda p, u: jnp.where(overflow, p,
                                           (p - lr * u.astype(jnp.float32)
                                            ).astype(p.dtype)),
                    live_params, updates)
                new_opt = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(overflow, o, n), new_opt,
                    live_opt)
            else:
                overflow = jnp.asarray(False)
                updates, new_opt = tx_update(grads, live_opt,
                                             live_params)
                new_params = jax.tree_util.tree_map(
                    lambda p, u: (p - lr * u.astype(jnp.float32)
                                  ).astype(p.dtype),
                    live_params, updates)

            new_scale = prec.update_loss_scale(
                state.scale, overflow, dynamic,
                loss_scale_window=fp16.loss_scale_window,
                min_loss_scale=fp16.min_loss_scale,
                consecutive_hysteresis=fp16.consecutive_hysteresis,
                init_hysteresis=fp16.hysteresis)

            new_state = TrainState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                scale=new_scale,
                rng=new_rng,
                skipped_steps=state.skipped_steps + overflow.astype(jnp.int32))
            metrics = {
                "loss": loss_sum / (scale * gas),
                # grads were already unscaled by 1/(scale*gas) above, so the
                # norm is reported as-is
                "grad_norm": grad_norm,
                "overflow": overflow,
                "loss_scale": new_scale.loss_scale,
            }
            return new_state, metrics

        metric_shardings = {k: self._repl()
                            for k in ("loss", "grad_norm", "overflow",
                                      "loss_scale")}
        return jax.jit(
            train_step,
            in_shardings=(self._state_shardings, None, None),
            out_shardings=(self._state_shardings, metric_shardings),
            donate_argnums=(0,))

    def _build_eval_step(self):
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        fetch_params = self._fetch_params

        def eval_step(state: TrainState, batch, rng):
            params = prec.cast_tree(fetch_params(state.params), compute_dtype)
            return loss_fn(params, batch, rng)

        return jax.jit(eval_step, out_shardings=self._repl())

    def _build_grad_step(self, host_grads: bool = False,
                         with_gmetrics: bool = False):
        """Imperative-mode micro step: grads for ONE micro-batch.

        ``host_grads=True`` (ZeRO-Infinity: offload_param + NVMe
        optimizer) lands the grads in pinned host memory via
        out_shardings — with unrolled layers XLA streams each layer's
        grad out as the backward produces it, so HBM never holds the
        full grad tree (the reference's offload grad buffers,
        ``zero/stage3.py`` partitioned gradient offload).  Grads keep
        the PARAM dtype in this mode (bf16 on the wire; the fp32
        accumulation fidelity lives in the NVMe moments, which cast per
        leaf — the measured fp32-cast temps are what pushed a 7B step
        80MB past a 16GB chip)."""
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        mesh = self.mesh
        grad_spec_tree = self._grad_spec_tree
        fetch_params = self._fetch_params

        def grad_step(state: TrainState, batch, rng):
            scale = state.scale.loss_scale

            def scaled_loss(p):
                loss = loss_fn(prec.cast_tree(p, compute_dtype), batch, rng)
                return (loss * scale.astype(loss.dtype)).astype(jnp.float32)

            loss_s, grads = jax.value_and_grad(scaled_loss)(
                fetch_params(state.params))
            if not host_grads:
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads)
            grads = constrain_tree(grads, grad_spec_tree, mesh)
            if with_gmetrics:
                # overflow/norm folded into the SAME program, computed
                # while the grads are still on device — the NVMe tier
                # would otherwise re-stream the full host grad tree (or
                # device_get two scalars per leaf) just for these two
                # reductions
                finite = jnp.array(True)
                sumsq = jnp.float32(0.0)
                for g in jax.tree_util.tree_leaves(grads):
                    finite &= jnp.isfinite(g).all()
                    sumsq += jnp.sum(jnp.square(g.astype(jnp.float32)))
                return loss_s / scale, grads, finite, sumsq
            return loss_s / scale, grads

        if not host_grads:
            return jax.jit(grad_step)
        host = jax.tree_util.tree_map(
            lambda s: s.with_memory_kind("pinned_host"),
            self._state_shardings.params)
        opts = None
        if jax.devices()[0].platform != "cpu":
            # the latency-hiding scheduler prefetches several layers'
            # host->HBM param copies concurrently — measured +2.7G over
            # budget at 7B on a 16GB chip; a serialized copy schedule
            # trades overlap for fitting (this tier is streaming-bound
            # anyway)
            opts = {"xla_tpu_enable_latency_hiding_scheduler": "false"}
        outs = (None, host, None, None) if with_gmetrics else (None, host)
        return jax.jit(grad_step, out_shardings=outs,
                       compiler_options=opts)

    def _build_apply_step(self):
        tx_update = self._tx_update
        plan = self.plan
        clip = self.config.gradient_clipping
        fp16 = self.config.fp16
        dynamic = self.dynamic_loss_scale
        gas = self.gas
        fetch_params = self._fetch_params
        fetch_opt = self._fetch_opt

        def apply_step(state: TrainState, grads, lr):
            scale = state.scale.loss_scale
            live_params = fetch_params(state.params)
            live_opt = fetch_opt(state.opt_state)
            inv = 1.0 / (scale * gas)
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            overflow = prec.has_inf_or_nan(grads)
            grad_norm = prec.global_norm(grads)
            if clip and clip > 0:
                grads, _ = prec.clip_by_global_norm(grads, clip, grad_norm)
            safe = jax.tree_util.tree_map(
                lambda g: jnp.where(overflow, jnp.zeros_like(g), g), grads)
            updates, new_opt = tx_update(safe, live_opt, live_params)
            new_params = jax.tree_util.tree_map(
                lambda p, u: jnp.where(overflow, p,
                                       (p - lr * u.astype(jnp.float32)
                                        ).astype(p.dtype)),
                live_params, updates)
            new_opt = jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new_opt,
                live_opt)
            new_scale = prec.update_loss_scale(
                state.scale, overflow, dynamic,
                loss_scale_window=fp16.loss_scale_window,
                min_loss_scale=fp16.min_loss_scale,
                consecutive_hysteresis=fp16.consecutive_hysteresis,
                init_hysteresis=fp16.hysteresis)
            rng, new_rng = jax.random.split(state.rng)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt, scale=new_scale,
                                   rng=new_rng,
                                   skipped_steps=state.skipped_steps +
                                   overflow.astype(jnp.int32))
            return new_state, {"grad_norm": grad_norm,
                               "overflow": overflow,
                               "loss_scale": new_scale.loss_scale}

        metric_shardings = {k: self._repl()
                            for k in ("grad_norm", "overflow", "loss_scale")}
        return jax.jit(apply_step,
                       in_shardings=(self._state_shardings, None, None),
                       out_shardings=(self._state_shardings,
                                      metric_shardings),
                       donate_argnums=(0,))

    # ------------------------------------------------------------------
    # NVMe-swapped optimizer step (ZeRO-Infinity tier)
    # ------------------------------------------------------------------

    def _nvme_train_step(self, gbatch, lr):
        """fwd+bwd per micro-batch on device, then the swapped optimizer
        step streaming Adam moments NVMe→HBM→NVMe (reference
        ``pipelined_optimizer_swapper`` semantics; see
        ``runtime/swap_tensor.py``)."""
        host_grads = bool(self.offload_param)
        # gas==1: overflow/norm fold into the grad-step program for free
        # (the metrics of the single micro ARE the final metrics).
        # gas>1 needs the norm of the SUM — fused per-micro reductions
        # would be paid and discarded, so skip them there.
        fused_metrics = self.gas == 1
        if getattr(self, "_nvme_grad_step_fn", None) is None:
            self._nvme_grad_step_fn = self._build_grad_step(
                host_grads=host_grads, with_gmetrics=fused_metrics)
        state = self.state
        rng = state.rng
        loss_sum, grads = None, None
        gmetrics = None
        for i in range(self.gas):
            mb = jax.tree_util.tree_map(lambda x: x[i], gbatch)
            rng, sub = jax.random.split(rng)
            if fused_metrics:
                loss, g, f, s2 = self._nvme_grad_step_fn(state, mb, sub)
                gmetrics = (~f, jnp.sqrt(s2))
            else:
                loss, g = self._nvme_grad_step_fn(state, mb, sub)
            loss_sum = loss if loss_sum is None else loss_sum + loss
            if grads is None:
                grads = g
            elif host_grads:
                grads = self._host_tree_add(grads, g)
            else:
                grads = jax.tree_util.tree_map(jnp.add, grads, g)
        # overlap the swap pipeline's HEAD with the in-flight bwd: the
        # grad dispatches above are async, so the first read window's
        # NVMe traffic (and any deferred write-back from the previous
        # step) runs while the device is still computing — the first
        # bucket's swap-in is free by the time apply() starts
        if hasattr(self.nvme_swapper, "start_prefetch"):
            self.nvme_swapper.start_prefetch()
        new_state, metrics = self._nvme_apply_grads(
            grads, lr, rng, leafwise=host_grads, gmetrics=gmetrics)
        metrics["loss"] = loss_sum / self.gas
        return new_state, metrics

    def _host_tree_add(self, a, b):
        """Leaf-by-leaf accumulate with pinned-host outputs: a whole-tree
        jitted add would stage the full fp32 grad tree in HBM, undoing
        the host-grad streaming."""
        if getattr(self, "_host_add_fn", None) is None:
            self._host_add_fn = {}
        flat_a, tree = jax.tree_util.tree_flatten(a)
        flat_b = jax.tree_util.tree_leaves(b)
        out = []
        for x, y in zip(flat_a, flat_b):
            sh = x.sharding.with_memory_kind("pinned_host")
            # sharding in the key: same-shape leaves with different specs
            # (e.g. col- vs row-parallel kernels) must not alias one
            # cached out_sharding
            key = (x.shape, str(x.dtype), sh)
            if key not in self._host_add_fn:
                self._host_add_fn[key] = jax.jit(
                    jnp.add, out_shardings=sh, donate_argnums=(0,))
            out.append(self._host_add_fn[key](x, y))
        return jax.tree_util.tree_unflatten(tree, out)

    def _nvme_apply_grads(self, grads, lr, rng, leafwise: bool = False,
                          gmetrics=None):
        """Overflow check + loss-scale update on device, then the
        bucketed/leafwise swapped Adam update (skipped entirely on
        overflow — the moments on disk are the authoritative state and
        simply stay put).

        ``leafwise``: grads live in pinned host memory — compute the
        overflow/norm reductions one leaf at a time so HBM holds one
        leaf, not the tree.  ``gmetrics``: (overflow, norm_raw) already
        computed (fused into the grad step) — skips the reduction pass
        entirely."""
        state = self.state
        if gmetrics is not None:
            overflow, norm_raw = gmetrics
        elif leafwise:
            if getattr(self, "_nvme_leaf_metric_fn", None) is None:
                # scalar accumulation stays ON DEVICE across the loop —
                # per-leaf blocking transfers turn this into one
                # round-trip per leaf (minutes at 7B through a remote
                # runtime); lazy chaining is one blocking read total
                self._nvme_leaf_metric_fn = jax.jit(
                    lambda g, fin, ss: (
                        fin & jnp.isfinite(g).all(),
                        ss + jnp.sum(jnp.square(g.astype(jnp.float32)))))
            fin = jnp.array(True)
            ss = jnp.float32(0.0)
            for leaf in jax.tree_util.tree_leaves(grads):
                fin, ss = self._nvme_leaf_metric_fn(leaf, fin, ss)
            overflow = ~fin
            norm_raw = jnp.sqrt(ss)
        else:
            if getattr(self, "_nvme_metrics_fn", None) is None:
                self._nvme_metrics_fn = jax.jit(
                    lambda g: (prec.has_inf_or_nan(g),
                               prec.global_norm(g)))
            overflow, norm_raw = self._nvme_metrics_fn(grads)
        # ONE blocking transfer for all three scalars: each device_get
        # is a full client round-trip (hundreds of ms through a remote
        # tunnel), and this sync is also the barrier the swap prefetch
        # overlaps — keep it singular
        scale_f, ovf, norm = jax.device_get(
            (state.scale.loss_scale, overflow, norm_raw))
        scale_f = float(scale_f)
        ovf = bool(ovf)
        inv = 1.0 / (scale_f * self.gas)
        norm = float(norm) * inv
        gscale = inv
        clip = self.config.gradient_clipping
        if clip and clip > 0:
            gscale *= min(1.0, clip / (norm + 1e-6))
        fp16 = self.config.fp16
        new_scale = prec.update_loss_scale(
            state.scale, overflow, self.dynamic_loss_scale,
            loss_scale_window=fp16.loss_scale_window,
            min_loss_scale=fp16.min_loss_scale,
            consecutive_hysteresis=fp16.consecutive_hysteresis,
            init_hysteresis=fp16.hysteresis)
        if ovf:
            new_params = state.params
            if hasattr(self.nvme_swapper, "cancel_prefetch"):
                # the skipped step must not leak its prefetched reads
                # into the next step's buffer pool
                self.nvme_swapper.cancel_prefetch()
        else:
            new_params = self.nvme_swapper.apply(state.params, grads,
                                                 lr=lr, gscale=gscale)
            stats = getattr(self.nvme_swapper, "stage_stats", None)
            if stats and self.config.wall_clock_breakdown:
                # per-stage swap waits join the breakdown timer group —
                # link-boundedness (and the SDC verify residual) is
                # measurable, not asserted
                for name in ("swap_in_wait", "bucket_update",
                             "swap_out_wait", "swap_verify"):
                    if stats.get(f"{name}_s") is not None:
                        self.timers(name).record(stats[f"{name}_s"])
        rng, new_rng = jax.random.split(rng)
        new_state = TrainState(
            step=state.step + 1, params=new_params,
            opt_state=state.opt_state, scale=new_scale, rng=new_rng,
            skipped_steps=state.skipped_steps + jnp.asarray(int(ovf),
                                                            jnp.int32))
        return new_state, {"grad_norm": norm, "overflow": ovf,
                           "loss_scale": new_scale.loss_scale}

    # ------------------------------------------------------------------
    # Batch plumbing
    # ------------------------------------------------------------------

    def _apply_curriculum(self, batch):
        """Truncate token batches to the current seqlen difficulty
        (reference ``engine.py curriculum_enabled_legacy`` +
        megatron-side truncation).  A DeviceBatch is already staged at
        full length and passes through untouched."""
        if isinstance(batch, DeviceBatch):
            return batch
        d = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)

        def trunc(x):
            x = np.asarray(x)
            return x[:, :d] if x.ndim >= 2 and x.shape[1] > d else x

        return jax.tree_util.tree_map(trunc, batch)

    def set_custom_curriculum_learning_schedule(self, schedule_fn) -> None:
        """Reference ``engine.set_custom_curriculum_learning_schedule``."""
        assert self.curriculum_scheduler is not None, (
            "curriculum_learning is not enabled")
        self.curriculum_scheduler.set_custom_get_difficulty(schedule_fn)

    def _to_gas_batch(self, batch):
        """[train_batch, ...] -> [gas, micro_global, ...] sharded arrays."""
        if isinstance(batch, DeviceBatch):
            return batch.tree
        gas = self.gas

        def reshape(x):
            x = np.asarray(x)
            assert x.shape[0] % gas == 0, (
                f"batch dim {x.shape[0]} not divisible by "
                f"gradient_accumulation_steps {gas}")
            return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])

        batch = jax.tree_util.tree_map(reshape, batch)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self.plan.batch_sharding(
                x.ndim, has_gas_dim=True, dtype=x.dtype)), batch)

    def put_batch(self, batch) -> "DeviceBatch":
        """Pre-stage a [train_batch, ...] batch on device in the engine's
        gas-sharded layout.  ``train_batch(batch=put_batch(b))`` then skips
        all per-step host work — useful when iterating over device-resident
        data or re-using a batch (benchmarks)."""
        return DeviceBatch(self._to_gas_batch(batch))

    def _lr_device(self) -> jax.Array:
        """Device scalar for the current LR, re-transferred only on change."""
        lr = float(self.get_lr()[0])
        if self._lr_cached_value != lr:
            self._lr_cached_value = lr
            self._lr_cached_dev = jax.device_put(
                np.float32(lr), NamedSharding(self.mesh, P()))
        return self._lr_cached_dev

    def _next_batch(self, data_iter):
        if data_iter is not None:
            return next(data_iter)
        if self._data_iter is None:
            assert self.training_dataloader is not None, (
                "train_batch needs a data_iter or training_data passed to "
                "initialize()")
            self._data_iter = iter(self.training_dataloader)
        return next(self._data_iter)

    # ------------------------------------------------------------------
    # Public API (reference parity)
    # ------------------------------------------------------------------

    def train_batch(self, data_iter: Optional[Iterator] = None,
                    batch: Any = None) -> jax.Array:
        """One full training step: GAS micro-batches fused in one compiled
        program (reference ``PipelineEngine.train_batch`` naming; for the
        plain engine this is forward+backward+step at once)."""
        if batch is None:
            batch = self._next_batch(data_iter)
        if self.curriculum_scheduler is not None:
            batch = self._apply_curriculum(batch)
        breakdown = self.config.wall_clock_breakdown
        if breakdown:
            self.timers("batch_prep").start()
        try:
            gbatch = self._to_gas_batch(batch)
        except Exception:
            if breakdown:
                self.timers("batch_prep").discard()
            raise
        if breakdown:
            self.timers("batch_prep").stop()
        if self._train_step_fn is None and self.nvme_swapper is None:
            self._train_step_fn = (
                self._build_onebit_train_step(gbatch)
                if self._onebit_axes is not None
                else self._build_train_step())
        lr = self._lr_device()

        self.tput_timer.start()
        if breakdown:
            self.timers(STEP_GLOBAL_TIMER).start()
        try:
            if self.nvme_swapper is not None:
                self.state, metrics = self._nvme_train_step(gbatch, lr)
            else:
                self.state, metrics = self._train_step_fn(self.state,
                                                          gbatch, lr)
        except CollectiveTimeout as e:
            if breakdown:
                self.timers(STEP_GLOBAL_TIMER).discard()
            self._handle_collective_timeout(e)    # re-raises
        except SwapCorruptionError as e:
            if breakdown:
                self.timers(STEP_GLOBAL_TIMER).discard()
            self._handle_swap_corruption(e)       # re-raises
        except Exception:
            if breakdown:
                self.timers(STEP_GLOBAL_TIMER).discard()
            raise
        if breakdown:
            # one fused XLA program covers fwd+bwd+step; the device-synced
            # bracket is the whole step (fwd/bwd are not separable without
            # deoptimizing — documented divergence from EngineTimers).
            # jit dispatch is async: sync on the result before stopping
            jax.block_until_ready(metrics)
            self.timers(STEP_GLOBAL_TIMER).stop()
        self._last_metrics = metrics
        self.global_steps += 1
        self.micro_steps += self.gas
        self.global_samples += self.config.train_batch_size
        self.lr_scheduler.step()
        self.tput_timer.stop(global_step=True)
        if self._skip_guard is not None:
            # costs one scalar sync per step; built only when
            # resilience.max_consecutive_skips > 0
            self._skip_guard.update(
                bool(jax.device_get(metrics["overflow"])),
                self.global_steps)
        if (self._desync is not None
                and self._desync.should_check(self.global_steps)):
            # cross-rank comparison of replica-identical scalars: a
            # corrupted collective (or diverged host-side stream) raises
            # GradientAnomalyError here instead of training on silently
            m = jax.device_get(metrics)
            self._desync.check({"loss": float(m["loss"]),
                                "grad_norm": float(m["grad_norm"])},
                               self.global_steps)

        if self.global_steps % self.config.steps_per_print == 0:
            m = jax.device_get(metrics)
            log_dist(
                f"step={self.global_steps} loss={float(m['loss']):.4f} "
                f"lr={self.get_lr()[0]:.3e} "
                f"grad_norm={float(m['grad_norm']):.3f} "
                f"loss_scale={float(m['loss_scale']):.0f}", ranks=[0])
            if breakdown:
                # elapsed accumulates across steps_per_print steps; report
                # per-step times like the reference EngineTimers (plus the
                # swap pipeline's stage waits when a swapped tier is live)
                names = ["batch_prep", STEP_GLOBAL_TIMER]
                names += [n for n in ("swap_in_wait", "bucket_update",
                                      "swap_out_wait", "swap_verify")
                          if self.timers.has_timer(n)]
                self.timers.log(names,
                                normalizer=self.config.steps_per_print)
            if (self.config.resilience.comm.straggler_report
                    and self.monitor is not None and self.monitor.enabled):
                # one small allgather per report (opt-in); names the
                # rank every eager collective waits for
                self.monitor.write_comm_health(dist.straggler_report(),
                                               self.global_samples)
            sdc = getattr(self.nvme_swapper, "sdc_counters", None)
            if (sdc is not None and self.monitor is not None
                    and self.monitor.enabled):
                # SDC detection/recovery counters stream alongside the
                # loss: a fleet host with flaky DRAM/storage shows up
                # as a climbing mismatch series, not a silent loss drift
                self.monitor.write_sdc_health(sdc, self.global_samples)
        if self.monitor is not None and self.monitor.enabled:
            m = jax.device_get(metrics)
            self.monitor.write_events([
                ("Train/Samples/train_loss", float(m["loss"]),
                 self.global_samples),
                ("Train/Samples/lr", self.get_lr()[0], self.global_samples),
            ])
        fp = self.config.flops_profiler
        if fp.enabled and self.global_steps == fp.profile_step:
            self._run_flops_profiler(gbatch, lr)
        return metrics["loss"]

    def _run_flops_profiler(self, gbatch, lr) -> None:
        """One-shot step profile at ``flops_profiler.profile_step``
        (reference wires this in ``engine._take_model_step``; here the
        whole fused step is re-traced once and costed from its jaxpr)."""
        from deepspeed_tpu.profiling import FlopsProfiler

        fp = self.config.flops_profiler
        if self._train_step_fn is None:
            # NVMe-offloaded step: no single fused program — cost the
            # fwd+bwd micro step (the dominant FLOPs; the optimizer apply
            # is a host-side bucket stream with no jaxpr)
            gfn = self._nvme_grad_step_fn or self._grad_step_fn
            assert gfn is not None
            mb = jax.tree_util.tree_map(lambda x: x[0], gbatch)
            prof = FlopsProfiler(gfn, ds_engine=self)
            prof.start_profile()
            prof.profile(self.state, mb, self.state.rng,
                         params=self.state.params)
            prof.print_model_profile(profile_step=fp.profile_step,
                                     module_depth=fp.module_depth,
                                     top_modules=fp.top_modules,
                                     detailed=fp.detailed,
                                     output_file=fp.output_file)
            prof.end_profile()
            return
        prof = FlopsProfiler(self._train_step_fn, ds_engine=self)
        prof.start_profile()
        # duration: the step jit donates the state, so it cannot be re-run
        # for measurement; reuse the wall_clock_breakdown bracket when on
        duration = 0.0
        if self.config.wall_clock_breakdown:
            duration = self.timers(STEP_GLOBAL_TIMER).last_interval
        prof.profile(self.state, gbatch, lr, params=self.state.params,
                     duration=duration)
        prof.print_model_profile(profile_step=fp.profile_step,
                                 module_depth=fp.module_depth,
                                 top_modules=fp.top_modules,
                                 detailed=fp.detailed,
                                 output_file=fp.output_file)
        prof.end_profile()

    def eval_batch(self, data_iter: Optional[Iterator] = None,
                   batch: Any = None) -> jax.Array:
        if batch is None:
            batch = self._next_batch(data_iter)
        batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(np.asarray(x),
                                     self.plan.batch_sharding(
                                         np.asarray(x).ndim,
                                         dtype=np.asarray(x).dtype)),
            batch)
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        rng = jax.random.fold_in(jax.random.PRNGKey(self.config.seed ^ 0x5EED),
                                 self.global_steps)
        return self._eval_step_fn(self.state, batch, rng)

    # -- imperative compat ----------------------------------------------

    def forward(self, batch) -> jax.Array:
        """Loss for one micro-batch; stashes it for ``backward``."""
        if self._onebit_axes is not None:
            raise NotImplementedError(
                "the 1-bit compressed optimizer path only supports the "
                "fused train_batch() API (local gradients never leave the "
                "compiled step); use train_batch or a non-1-bit optimizer")
        self._fwd_batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(np.asarray(x),
                                     self.plan.batch_sharding(
                                         np.asarray(x).ndim,
                                         dtype=np.asarray(x).dtype)),
            batch)
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        rng = jax.random.fold_in(self.state.rng, self.micro_steps)
        self._fwd_rng = rng
        return self._eval_step_fn(self.state, self._fwd_batch, rng)

    def backward(self, loss=None) -> None:
        """Accumulate grads for the stashed micro-batch."""
        assert getattr(self, "_fwd_batch", None) is not None, \
            "backward() without forward()"
        if self._grad_step_fn is None:
            self._grad_step_fn = self._build_grad_step()
        _, grads = self._grad_step_fn(self.state, self._fwd_batch,
                                      self._fwd_rng)
        if self._pending_grads is None:
            self._pending_grads = grads
        else:
            self._pending_grads = jax.tree_util.tree_map(
                jnp.add, self._pending_grads, grads)
        self.micro_steps += 1
        self._profile_batch = self._fwd_batch  # kept for flops profiling
        self._fwd_batch = None

    def is_gradient_accumulation_boundary(self) -> bool:
        return self.micro_steps % self.gas == 0

    def step(self) -> None:
        """Apply accumulated grads at a GAS boundary (no-op otherwise,
        matching reference engine.step semantics)."""
        if not self.is_gradient_accumulation_boundary():
            return
        assert self._pending_grads is not None, "step() without backward()"
        if self.nvme_swapper is not None:
            lr = jnp.asarray(self.get_lr()[0], jnp.float32)
            fp = self.config.flops_profiler
            if fp.enabled and self.global_steps + 1 == fp.profile_step:
                # fwd+bwd only: the swapped optimizer apply is a host-side
                # leaf loop with no single jaxpr to cost
                self._profile_imperative_step(lr)
            self.state, self._last_metrics = self._nvme_apply_grads(
                self._pending_grads, lr, self.state.rng)
            self._pending_grads = None
            self.global_steps += 1
            self.lr_scheduler.step()
            return
        if self._apply_step_fn is None:
            self._apply_step_fn = self._build_apply_step()
        lr = jnp.asarray(self.get_lr()[0], jnp.float32)
        fp = self.config.flops_profiler
        if fp.enabled and self.global_steps + 1 == fp.profile_step:
            self._profile_imperative_step(lr)
        self.state, self._last_metrics = self._apply_step_fn(
            self.state, self._pending_grads, lr)
        self._pending_grads = None
        self.global_steps += 1
        self.lr_scheduler.step()
        if self._skip_guard is not None:
            self._skip_guard.update(
                bool(jax.device_get(self._last_metrics["overflow"])),
                self.global_steps)

    def _profile_imperative_step(self, lr) -> None:
        """Flops profile for the imperative fwd/bwd/step path: cost the
        grad fn (fwd+bwd, the dominant FLOPs) and the optimizer apply,
        merged into one report (the fused ``train_batch`` path instead
        profiles its single step program)."""
        from deepspeed_tpu.profiling import FlopsProfiler
        from deepspeed_tpu.profiling.flops_profiler import (_merge,
                                                            profile_fn)

        fp = self.config.flops_profiler
        prof = FlopsProfiler(self._grad_step_fn, ds_engine=self)
        prof.start_profile()
        prof.profile(self.state, self._profile_batch, self._fwd_rng,
                     params=self.state.params)
        if self._apply_step_fn is not None:     # nvme step has no jaxpr
            apply_tree = profile_fn(self._apply_step_fn, self.state,
                                    self._pending_grads, lr)
            _merge(prof._tree, apply_tree)
        prof.print_model_profile(profile_step=fp.profile_step,
                                 module_depth=fp.module_depth,
                                 top_modules=fp.top_modules,
                                 detailed=fp.detailed,
                                 output_file=fp.output_file)
        prof.end_profile()

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        save_latest: bool = True,
                        async_save: Optional[bool] = None) -> str:
        from deepspeed_tpu.checkpoint.engine import save_checkpoint as _save

        return _save(self, save_dir, tag=tag, client_state=client_state,
                     save_latest=save_latest, async_save=async_save)

    def offload_states(self, include: Optional[Tuple[str, ...]] = None
                       ) -> None:
        """Move optimizer state (and optionally params) to host memory at
        runtime (reference ``engine.offload_states:3839`` /
        ``zero/offload_states.py``): frees HBM between training phases —
        e.g. while a hybrid engine generates.  ``include``: subset of
        ("optimizer", "params"); default optimizer only.  The next
        train step streams them back in-graph (H2D fetch), or call
        :meth:`reload_states` to move them back eagerly."""
        if self.mesh.devices.flat[0].platform == "cpu":
            logger.warning("offload_states: backend has no host memory "
                           "space; no-op")
            return
        include = include or ("optimizer",)
        from deepspeed_tpu.utils.sharding import memory_space

        to_host = memory_space("pinned_host")

        def host_kind(shardings):
            return jax.tree_util.tree_map(
                lambda s: s.with_memory_kind("pinned_host"), shardings)

        state = self.state
        shardings = self._state_shardings
        if "optimizer" in include:
            host_opt = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, to_host), state.opt_state)
            self._fetch_opt = (
                lambda o, _s=jax.tree_util.tree_map(
                    lambda x: x.sharding, state.opt_state):
                jax.device_put(o, _s))
            state = state.replace(opt_state=host_opt)
            shardings = shardings.replace(
                opt_state=host_kind(shardings.opt_state))
        if "params" in include:
            host_p = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, to_host), state.params)
            self._fetch_params = (
                lambda p, _s=jax.tree_util.tree_map(
                    lambda x: x.sharding, state.params):
                jax.device_put(p, _s))
            state = state.replace(params=host_p)
            shardings = shardings.replace(
                params=host_kind(shardings.params))
        self.state = state
        # the jitted steps bake in_shardings AND the fetch closures; every
        # cached program must rebuild against the host-resident layout
        self._state_shardings = shardings
        self._invalidate_compiled_steps()
        log_dist(f"offload_states: {include} moved to pinned host memory",
                 ranks=[0])

    def reload_states(self) -> None:
        """Inverse of :meth:`offload_states` (reference
        ``engine.reload_states:3871``)."""
        if self.mesh.devices.flat[0].platform == "cpu":
            return
        from deepspeed_tpu.utils.sharding import memory_space

        to_dev = memory_space("device")

        def dev_kind(shardings):
            return jax.tree_util.tree_map(
                lambda s: s.with_memory_kind("device"), shardings)

        self.state = self.state.replace(
            opt_state=jax.tree_util.tree_map(
                lambda x: jax.device_put(x, to_dev), self.state.opt_state),
            params=jax.tree_util.tree_map(
                lambda x: jax.device_put(x, to_dev), self.state.params))
        self._state_shardings = self._state_shardings.replace(
            opt_state=dev_kind(self._state_shardings.opt_state),
            params=dev_kind(self._state_shardings.params))
        self._fetch_opt = lambda o: o
        self._fetch_params = lambda p: p
        self._invalidate_compiled_steps()
        log_dist("reload_states: state back in device memory", ranks=[0])

    def _invalidate_compiled_steps(self) -> None:
        self._train_step_fn = None
        self._eval_step_fn = None
        self._grad_step_fn = None
        self._nvme_grad_step_fn = None
        self._apply_step_fn = None

    def save_16bit_model(self, save_dir: str,
                         output_file: str = "pytorch_model.bin") -> str:
        """Consolidated compute-dtype weights for serving (reference
        ``engine.save_16bit_model``)."""
        from deepspeed_tpu.checkpoint.engine import \
            save_16bit_model as _save16

        return _save16(self, save_dir, output_file)

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True):
        from deepspeed_tpu.checkpoint.engine import load_checkpoint as _load

        return _load(self, load_dir, tag=tag,
                     load_optimizer_states=load_optimizer_states,
                     load_lr_scheduler_states=load_lr_scheduler_states)

    def wait_checkpoint(self) -> None:
        """Join an in-flight async checkpoint save (no-op otherwise)."""
        from deepspeed_tpu.checkpoint.engine import wait_checkpoint as _wait

        _wait(self)

    # -- preemption / fault tolerance (resilience/) -----------------------

    def emergency_checkpoint(self, save_dir: str) -> str:
        """Drain any in-flight async save, then take a synchronous
        checkpoint — the last-gasp save a preemption notice triggers.
        A failed in-flight save is logged and superseded (this snapshot
        is strictly newer), never allowed to block the emergency
        write."""
        try:
            self.wait_checkpoint()
        except BaseException as e:
            logger.error(f"emergency checkpoint: in-flight async save "
                         f"failed ({e!r}); writing a fresh synchronous "
                         "checkpoint")
            self._ckpt_saver = None           # drop the poisoned saver
        return self.save_checkpoint(
            save_dir, tag=f"emergency_step{self.global_steps}",
            async_save=False)

    def _handle_collective_timeout(self, e: CollectiveTimeout) -> None:
        """Route a collective timeout through the preemption path: a
        peer is gone or the transport wedged, so this process must stop
        cleanly and let the elastic layer restart the job.  The
        emergency checkpoint is an ATTEMPT — its own collectives may hit
        the same dead peer (the watchdog bounds them too), and a failed
        save must not mask the original timeout."""
        self.comm_timed_out = True
        logger.error(f"collective timeout during training step: {e}")
        save_dir = self._preemption_save_dir
        if save_dir:
            # the postmortem timeline lands NEXT TO the emergency
            # checkpoint (the raise site already dumped to the default
            # flight dir; this copy is the one operators find first)
            from deepspeed_tpu.telemetry import flight

            flight.dump_on_fault("collective_timeout", e, dir=save_dir)
            try:
                path = self.emergency_checkpoint(save_dir)
                logger.error(f"emergency checkpoint committed at {path}; "
                             "aborting for elastic restart")
            except BaseException as ce:
                logger.error(f"emergency checkpoint failed under comm "
                             f"timeout ({ce!r}); aborting without it")
        raise e

    def _handle_swap_corruption(self, e: SwapCorruptionError) -> None:
        """Route persistent silent data corruption in the swap path
        through the preemption machinery: the corrupt swap file is
        already quarantined and the swap state invalidated, so the
        right move is a last-gasp checkpoint (params are intact — the
        corruption was caught BEFORE the update consumed it) and a
        clean abort; the elastic agent then restarts from the newest
        verified checkpoint instead of training on garbage."""
        self.swap_corrupted = True
        logger.error(f"silent data corruption in the NVMe swap path: {e}")
        save_dir = self._preemption_save_dir
        if save_dir:
            from deepspeed_tpu.telemetry import flight

            flight.dump_on_fault("swap_corruption", e, dir=save_dir)
            try:
                path = self.emergency_checkpoint(save_dir)
                logger.error(f"emergency checkpoint committed at {path}; "
                             "aborting for elastic restart")
            except BaseException as ce:
                logger.error(f"emergency checkpoint failed under swap "
                             f"corruption ({ce!r}); aborting without it")
        raise e

    def install_preemption_handler(self, save_dir: str, signals=None,
                                   exit_after: bool = True) -> None:
        """SIGTERM hook (TPU preemption notice): drains the async saver,
        takes an emergency synchronous checkpoint, then re-delivers the
        signal to the previous disposition (``exit_after=False`` returns
        to the interrupted code instead — tests, or jobs that drain
        work themselves).  Call :meth:`uninstall_preemption_handler` to
        restore the prior handlers."""
        import signal as _signal

        signals = tuple(signals or (_signal.SIGTERM,))
        prev = {}

        def _handler(signum, frame):
            self.preempted = True
            logger.error(f"signal {signum}: preemption notice — taking "
                         "emergency checkpoint")
            from deepspeed_tpu.telemetry import flight

            flight.dump_on_fault("sigterm_preemption", dir=save_dir,
                                 extra={"signal": int(signum),
                                        "step": int(self.global_steps)})
            path = self.emergency_checkpoint(save_dir)
            logger.error(f"emergency checkpoint committed at {path}")
            if not exit_after:
                return
            old = prev.get(signum, _signal.SIG_DFL)
            if callable(old):
                old(signum, frame)
            else:
                _signal.signal(signum, old)
                _signal.raise_signal(signum)

        for s in signals:
            prev[s] = _signal.signal(s, _handler)
        self._preemption_prev_handlers = prev
        # collective timeouts reuse this dir for their emergency save
        self._preemption_save_dir = save_dir

    def uninstall_preemption_handler(self) -> None:
        import signal as _signal

        if self._preemption_prev_handlers:
            for s, old in self._preemption_prev_handlers.items():
                _signal.signal(s, old)
            self._preemption_prev_handlers = None

    # -- misc -------------------------------------------------------------

    def get_global_grad_norm(self) -> Optional[float]:
        """Global (pre-clip) gradient norm of the most recent step
        (reference ``engine.py`` ``get_global_grad_norm``)."""
        m = getattr(self, "_last_metrics", None)
        if m is None:
            return None
        return float(jax.device_get(m["grad_norm"]))

    def module_state_dict(self):
        return jax.device_get(self.state.params)

    def train(self, mode: bool = True):  # API parity; no mode flag needed
        return self

    def eval(self):
        return self
