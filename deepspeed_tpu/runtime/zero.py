"""ZeRO as sharding layouts.

TPU-native re-design of the reference's ZeRO optimizers
(``runtime/zero/stage_1_and_2.py:97``, ``runtime/zero/stage3.py:112``,
``runtime/zero/partition_parameters.py``): on TPU the three stages are
*sharding layouts* on the train state, and XLA/GSPMD emits the collectives
the reference issues by hand (reduce-scatter of grads ≡ the
``average_tensor`` hot loop; per-layer all-gather ≡ the param coordinator's
``fetch_sub_module``):

- stage 0: params/grads/opt-state replicated; grads all-reduced.
- stage 1: optimizer state sharded over the ZeRO axes.
- stage 2: stage 1 + gradients constrained to the sharded layout, so XLA
  reduce-scatters instead of all-reducing (``psum_scatter`` on the wire).
- stage 3: parameters sharded too; all-gather materializes each layer's
  params at use (FSDP). Small params stay replicated below
  ``stage3_param_persistence_threshold`` — same knob, same semantics: they
  are "persistent" exceptions that never pay a gather.

No module hooks, no prefetch tracer: XLA's latency-hiding scheduler overlaps
the gathers; scan-over-layers in the model bounds live parameters the way
``stage3_max_live_parameters`` does.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.utils.logging import log_dist


class ZeroShardingPlan:
    """Computes per-leaf PartitionSpecs for a given stage and topology."""

    def __init__(self, topology: MeshTopology, stage: int,
                 persistence_threshold: int = 100_000,
                 hpz_partition_size: int = 1):
        assert stage in (0, 1, 2, 3)
        self.topology = topology
        self.stage = stage
        self.persistence_threshold = persistence_threshold
        self.hpz_partition_size = hpz_partition_size
        # ZeRO partitions over data+expert+seq (the reference's
        # seq_data_parallel_group, engine.py:1603)
        self.axes: Tuple[str, ...] = tuple(
            a for a in topology.zero_axes if topology.axis_size(a) > 1)
        self.partitions = int(np.prod(
            [topology.axis_size(a) for a in self.axes])) if self.axes else 1

    # -- per-leaf spec ----------------------------------------------------

    def _shardable_dim(self, shape: Tuple[int, ...]) -> Optional[int]:
        """Pick the dimension to shard: largest dim divisible by the
        partition count (ties → earliest)."""
        best = None
        best_size = 0
        for i, d in enumerate(shape):
            if d % self.partitions == 0 and d > best_size:
                best, best_size = i, d
        return best

    def leaf_spec(self, shape: Tuple[int, ...], sharded: bool) -> P:
        """PartitionSpec for one array of ``shape``."""
        if not sharded or not self.axes or len(shape) == 0:
            return P()
        if int(np.prod(shape)) <= self.persistence_threshold:
            return P()  # persistent (replicated) small param
        dim = self._shardable_dim(shape)
        if dim is None:
            return P()
        spec = [None] * len(shape)
        spec[dim] = self.axes if len(self.axes) > 1 else self.axes[0]
        return P(*spec)

    # -- tree-level specs -------------------------------------------------

    def param_specs(self, params):
        """Stage 3 shards params; stages 0-2 replicate them."""
        sharded = self.stage >= 3
        return jax.tree_util.tree_map(
            lambda x: self.leaf_spec(x.shape, sharded), params)

    def grad_specs(self, params):
        """Stage >= 2 keeps grads in the sharded layout (reduce-scatter)."""
        sharded = self.stage >= 2
        return jax.tree_util.tree_map(
            lambda x: self.leaf_spec(x.shape, sharded), params)

    def opt_state_specs(self, opt_state):
        """Stage >= 1 shards optimizer moments. Rule: any leaf big enough to
        shard follows the same layout as a param of its shape; scalars and
        small leaves replicate."""
        sharded = self.stage >= 1
        return jax.tree_util.tree_map(
            lambda x: self.leaf_spec(getattr(x, "shape", ()), sharded), opt_state)

    # -- shardings --------------------------------------------------------

    def _to_sharding(self, spec_tree):
        mesh = self.topology.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def param_shardings(self, params):
        return self._to_sharding(self.param_specs(params))

    def grad_shardings(self, params):
        return self._to_sharding(self.grad_specs(params))

    def opt_state_shardings(self, opt_state):
        return self._to_sharding(self.opt_state_specs(opt_state))

    def batch_spec(self, batch_ndim: int, has_gas_dim: bool = False) -> P:
        """Batch arrays shard their batch dim over (data, expert): each
        data-parallel (and expert-parallel) member sees different samples.
        The ``seq`` axis shards the sequence dim when sequence parallelism is
        active (handled by the sequence engine; here seq stays on batch)."""
        axes = tuple(a for a in ("data", "expert")
                     if self.topology.axis_size(a) > 1)
        specs = []
        if has_gas_dim:
            specs.append(None)  # scan (GAS) dim never sharded
        specs.append(axes if len(axes) > 1 else (axes[0] if axes else None))
        while len(specs) < batch_ndim:
            specs.append(None)
        return P(*specs)

    def batch_sharding(self, batch_ndim: int, has_gas_dim: bool = False) -> NamedSharding:
        return NamedSharding(self.topology.mesh,
                             self.batch_spec(batch_ndim, has_gas_dim))

    def describe(self, params) -> str:
        n_sharded = 0
        n_total = 0
        bytes_sharded = 0
        bytes_total = 0
        for leaf, spec in zip(jax.tree_util.tree_leaves(params),
                              jax.tree_util.tree_leaves(
                                  self.param_specs(params),
                                  is_leaf=lambda x: isinstance(x, P))):
            n_total += 1
            sz = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            bytes_total += sz
            if any(s is not None for s in spec):
                n_sharded += 1
                bytes_sharded += sz
        return (f"ZeRO stage {self.stage}: {n_sharded}/{n_total} param tensors "
                f"sharded over {self.axes} ({self.partitions} partitions), "
                f"{bytes_sharded / max(bytes_total, 1):.0%} of param bytes")


def constrain_tree(tree, spec_tree, mesh: Mesh):
    """Apply ``with_sharding_constraint`` leaf-wise (used on grads inside the
    step for stage >= 2)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
        tree, spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def log_plan(plan: ZeroShardingPlan, params) -> None:
    log_dist(plan.describe(params), ranks=[0])
