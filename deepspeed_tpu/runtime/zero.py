"""ZeRO as sharding layouts.

TPU-native re-design of the reference's ZeRO optimizers
(``runtime/zero/stage_1_and_2.py:97``, ``runtime/zero/stage3.py:112``,
``runtime/zero/partition_parameters.py``): on TPU the three stages are
*sharding layouts* on the train state, and XLA/GSPMD emits the collectives
the reference issues by hand (reduce-scatter of grads ≡ the
``average_tensor`` hot loop; per-layer all-gather ≡ the param coordinator's
``fetch_sub_module``):

- stage 0: params/grads/opt-state replicated; grads all-reduced.
- stage 1: optimizer state sharded over the ZeRO axes.
- stage 2: stage 1 + gradients constrained to the sharded layout, so XLA
  reduce-scatters instead of all-reducing (``psum_scatter`` on the wire).
- stage 3: parameters sharded too; all-gather materializes each layer's
  params at use (FSDP). Small params stay replicated below
  ``stage3_param_persistence_threshold`` — same knob, same semantics: they
  are "persistent" exceptions that never pay a gather.

No module hooks, no prefetch tracer: XLA's latency-hiding scheduler overlaps
the gathers; scan-over-layers in the model bounds live parameters the way
``stage3_max_live_parameters`` does.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.utils.logging import log_dist


class ZeroShardingPlan:
    """Computes per-leaf PartitionSpecs for a given stage and topology."""

    def __init__(self, topology: MeshTopology, stage: int,
                 persistence_threshold: int = 100_000,
                 hpz_partition_size: int = 1):
        assert stage in (0, 1, 2, 3)
        self.topology = topology
        self.stage = stage
        self.persistence_threshold = persistence_threshold
        self.hpz_partition_size = hpz_partition_size
        # ZeRO partitions over data+expert+seq (the reference's
        # seq_data_parallel_group, engine.py:1603)
        self.axes: Tuple[str, ...] = tuple(
            a for a in topology.zero_axes if topology.axis_size(a) > 1)
        self.partitions = int(np.prod(
            [topology.axis_size(a) for a in self.axes])) if self.axes else 1
        # hpZ (ZeRO++ secondary partition, groups.py:650): stage-3 PARAMS
        # shard only over the node-local data_sub axis — cheap all-gathers
        # over intra-node ICI — while grads/opt state keep the full extent
        self.param_axes: Tuple[str, ...] = self.axes
        if hpz_partition_size > 1 and stage >= 3:
            from deepspeed_tpu.parallel.topology import HPZ_AXIS

            if topology.hpz_partition_size != hpz_partition_size:
                raise ValueError(
                    f"zero_hpz_partition_size={hpz_partition_size} but the "
                    f"mesh's data_sub axis is {topology.hpz_partition_size} "
                    "wide — build the mesh with initialize_mesh(..., "
                    "hpz=<size>) (the engine does this automatically)")
            self.param_axes = tuple(a for a in self.axes if a == HPZ_AXIS)

    # -- per-leaf spec ----------------------------------------------------

    def leaf_spec(self, shape: Tuple[int, ...], sharded: bool,
                  base: Optional[P] = None,
                  axes: Optional[Tuple[str, ...]] = None) -> P:
        """PartitionSpec for one array of ``shape``.

        ``base`` carries pre-existing model-parallel sharding (TP/expert axis
        names from flax metadata or AutoTP); ZeRO composes with it by
        claiming one of the still-unsharded dims.  TP sharding is always
        preserved, even when ZeRO itself doesn't shard this tree.
        """
        ndim = len(shape)
        spec = list(base) if base is not None else []
        spec = spec[:ndim] + [None] * (ndim - len(spec))
        has_base = any(s is not None for s in spec)
        my_axes = self.axes if axes is None else axes

        def out():
            return P(*spec) if has_base else P()

        if not sharded or not my_axes or ndim == 0:
            return out()
        if int(np.prod(shape)) <= self.persistence_threshold and not has_base:
            return P()  # persistent (replicated) small param
        # ZeRO may only claim axes the base spec doesn't already use
        base_axes = set()
        for s in spec:
            for ax in (s,) if isinstance(s, str) else (s or ()):
                base_axes.add(ax)
        axes = tuple(a for a in my_axes if a not in base_axes)
        if not axes:
            return out()
        partitions = int(np.prod([self.topology.axis_size(a) for a in axes]))
        best, best_size = None, 0
        for i, d in enumerate(shape):
            if spec[i] is None and d % partitions == 0 and d > best_size:
                best, best_size = i, d
        if best is None:
            return out()
        spec[best] = axes if len(axes) > 1 else axes[0]
        return P(*spec)

    # -- tree-level specs -------------------------------------------------

    def _specs(self, params, sharded: bool, base_specs, axes=None):
        if base_specs is None:
            return jax.tree_util.tree_map(
                lambda x: self.leaf_spec(x.shape, sharded, axes=axes), params)
        return jax.tree_util.tree_map(
            lambda x, b: self.leaf_spec(x.shape, sharded, b, axes=axes),
            params, base_specs)

    def param_specs(self, params, base_specs=None):
        """Stage 3 shards params (over ``param_axes`` — restricted to the
        node-local sub-axis under hpZ); stages 0-2 keep only the base (TP)
        spec."""
        return self._specs(params, self.stage >= 3, base_specs,
                           axes=self.param_axes)

    def grad_specs(self, params, base_specs=None):
        """Stage >= 2 keeps grads in the sharded layout (reduce-scatter)."""
        return self._specs(params, self.stage >= 2, base_specs)

    def moment_specs(self, params, base_specs=None):
        """Per-param layout of the optimizer moments (stage >= 1 sharded) —
        the layout the fused-optimizer shard_map runs in: each device updates
        its own shard of (g, p, m, v), the reference's stage-1/2 ``step``
        partition semantics (stage_1_and_2.py ~1800s)."""
        return self._specs(params, self.stage >= 1, base_specs)

    @staticmethod
    def _path_key(kp) -> Tuple[str, ...]:
        return tuple(str(k) for k in kp)

    def opt_state_specs(self, opt_state, base_specs=None):
        """Stage >= 1 shards optimizer moments.

        Moment trees inside optax states mirror the param tree, so each opt
        leaf inherits the base (TP) spec of the param whose tree path is a
        suffix of the opt leaf's path; scalars and unmatched leaves fall back
        to shape-based ZeRO sharding only.
        """
        sharded = self.stage >= 1
        suffix_map = {}
        if base_specs is not None:
            for kp, spec in jax.tree_util.tree_flatten_with_path(
                    base_specs, is_leaf=lambda x: isinstance(x, P))[0]:
                suffix_map[self._path_key(kp)] = spec

        def spec_for(kp, leaf):
            shape = tuple(getattr(leaf, "shape", ()))
            base = None
            keys = self._path_key(kp)
            for i in range(len(keys)):
                if keys[i:] in suffix_map:
                    base = suffix_map[keys[i:]]
                    break
            return self.leaf_spec(shape, sharded, base)

        return jax.tree_util.tree_map_with_path(spec_for, opt_state)

    # -- shardings --------------------------------------------------------

    def _to_sharding(self, spec_tree):
        mesh = self.topology.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def param_shardings(self, params, base_specs=None):
        return self._to_sharding(self.param_specs(params, base_specs))

    def grad_shardings(self, params, base_specs=None):
        return self._to_sharding(self.grad_specs(params, base_specs))

    def opt_state_shardings(self, opt_state, base_specs=None):
        return self._to_sharding(self.opt_state_specs(opt_state, base_specs))

    def batch_spec(self, batch_ndim: int, has_gas_dim: bool = False,
                   dtype=None) -> P:
        """Batch arrays shard their batch dim over (data, expert); with
        sequence parallelism active the dim after batch (the sequence dim of
        [B, S] token arrays) shards over ``seq`` — inputs then arrive
        seq-sharded exactly like the reference's Ulysses input contract
        ([s/P, b, h], ``sequence/layer.py``).

        The seq rule applies only to INTEGER arrays (token ids / masks /
        position ids): a float [B, features] input has no sequence dim,
        and guessing one would mis-shard it.  Pass ``dtype`` to engage
        the check; ``dtype=None`` keeps the token-array assumption for
        backward compatibility."""
        axes = tuple(a for a in ("data", "data_sub", "expert")
                     if self.topology.axis_size(a) > 1)
        specs = []
        if has_gas_dim:
            specs.append(None)  # scan (GAS) dim never sharded
        specs.append(axes if len(axes) > 1 else (axes[0] if axes else None))
        token_like = dtype is None or np.issubdtype(np.dtype(dtype),
                                                    np.integer)
        if (len(specs) < batch_ndim and token_like and
                self.topology.axis_size("seq") > 1):
            specs.append("seq")
        while len(specs) < batch_ndim:
            specs.append(None)
        return P(*specs)

    def batch_sharding(self, batch_ndim: int, has_gas_dim: bool = False,
                       dtype=None) -> NamedSharding:
        return NamedSharding(self.topology.mesh,
                             self.batch_spec(batch_ndim, has_gas_dim,
                                             dtype=dtype))

    def describe(self, params, base_specs=None) -> str:
        n_sharded = 0
        n_total = 0
        bytes_sharded = 0
        bytes_total = 0
        for leaf, spec in zip(jax.tree_util.tree_leaves(params),
                              jax.tree_util.tree_leaves(
                                  self.param_specs(params, base_specs),
                                  is_leaf=lambda x: isinstance(x, P))):
            n_total += 1
            sz = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            bytes_total += sz
            if any(s is not None for s in spec):
                n_sharded += 1
                bytes_sharded += sz
        return (f"ZeRO stage {self.stage}: {n_sharded}/{n_total} param tensors "
                f"sharded over {self.axes} ({self.partitions} partitions), "
                f"{bytes_sharded / max(bytes_total, 1):.0%} of param bytes")


def constrain_tree(tree, spec_tree, mesh: Mesh):
    """Apply ``with_sharding_constraint`` leaf-wise (used on grads inside the
    step for stage >= 2)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
        tree, spec_tree,
        is_leaf=lambda x: isinstance(x, P))


