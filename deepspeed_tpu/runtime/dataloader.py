"""Data loading.

Equivalent of the reference's ``runtime/dataloader.py``
(``DeepSpeedDataLoader`` + ``RepeatingLoader``).  In the single-controller
model the loader yields *global* batches (every host feeds its local chips
from a globally-consistent stream); the engine shards the batch over the
``data`` mesh axis on device_put.  Works with any iterable / indexable
dataset yielding numpy arrays, dicts of arrays, or tuples.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np

import jax

from deepspeed_tpu.utils.logging import logger


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference
    ``RepeatingLoader``).  Resumable when the wrapped loader is: the
    state calls delegate, and restoring re-creates the live iterator so
    the stream continues from the restored cursor."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    def state_dict(self) -> Optional[Dict[str, Any]]:
        sd = getattr(self.loader, "state_dict", None)
        return sd() if callable(sd) else None

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.loader.load_state_dict(state)
        self.data_iter = iter(self.loader)


def _stack(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[i] for s in samples])
                           for i in range(len(first)))
    return np.stack(samples)


class DeepSpeedDataLoader:
    """Batches an indexable dataset into global batches of
    ``batch_size`` samples, optionally shuffled per epoch with a seeded RNG
    (deterministic across hosts — the TPU analogue of the reference's
    DistributedSampler consistency check, engine.py:434).

    Resumable: the loader tracks ``(seed, epoch, cursor)`` — the
    in-epoch batch position — through :meth:`state_dict` /
    :meth:`load_state_dict`, and the engine persists it in the
    checkpoint's extra payload.  A restart therefore CONTINUES
    mid-epoch from the next unseen batch instead of replaying (double-
    training) or skipping (never seeing) the interrupted epoch's data;
    the shuffle permutation is a pure function of ``seed + epoch``, so
    the resumed sequence is identical to the uninterrupted one."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 seed: int = 1234, drop_last: bool = True,
                 collate_fn=None, world_size: int = 1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _stack
        self.world_size = int(world_size)   # recorded for elastic resume
        self.epoch = 0              # epoch the NEXT batch comes from
        self.cursor = 0             # batches already served this epoch
        if not hasattr(dataset, "__len__") or not hasattr(dataset, "__getitem__"):
            raise TypeError("DeepSpeedDataLoader needs an indexable dataset; "
                            "wrap pure iterators with RepeatingLoader instead")

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        stop = (n // self.batch_size) * self.batch_size if self.drop_last else n
        starts = range(0, stop, self.batch_size)
        for bi, start in enumerate(starts):
            if bi < self.cursor:
                continue            # resume mid-epoch: skip served batches
            sel = idx[start:start + self.batch_size]
            batch = self.collate_fn([self.dataset[int(i)] for i in sel])
            self.cursor = bi + 1
            yield batch
        self.epoch += 1
        self.cursor = 0

    # -- resumable state -------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {"seed": int(self.seed), "epoch": int(self.epoch),
                "cursor": int(self.cursor),
                "batch_size": int(self.batch_size),
                "world_size": int(self.world_size)}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.seed = int(state["seed"])
        self.epoch = int(state["epoch"])
        cursor = int(state["cursor"])
        saved_bs = int(state.get("batch_size", self.batch_size))
        saved_world = int(state.get("world_size", self.world_size))
        if saved_bs != self.batch_size:
            # elastic re-slice changed the GLOBAL batch size: the cursor
            # counts batches of the OLD size, so re-map it through the
            # sample position.  Floor division re-visits at most one
            # partial batch rather than skipping samples.
            samples = cursor * saved_bs
            cursor = samples // self.batch_size
            if samples % self.batch_size:
                logger.warning(
                    f"dataloader resume: global batch {saved_bs} -> "
                    f"{self.batch_size} does not divide the {samples} "
                    f"consumed samples; re-visiting "
                    f"{samples % self.batch_size} samples of batch "
                    f"{cursor} rather than dropping them")
            logger.info(
                f"dataloader resume: re-mapped cursor {state['cursor']} "
                f"(batch {saved_bs}, world {saved_world}) -> {cursor} "
                f"(batch {self.batch_size}, world {self.world_size})")
        elif saved_world != self.world_size:
            # same global batch at a different world (elastic contract:
            # constant global batch across the menu) -> the cursor is a
            # count of GLOBAL batches and remains exact; log the
            # re-slice so resumes are auditable
            logger.info(
                f"dataloader resume: world {saved_world} -> "
                f"{self.world_size} with unchanged global batch "
                f"{self.batch_size}; cursor {cursor} carries over")
        self.cursor = cursor


def shard_batch(batch, sharding) -> Any:
    """device_put every array in the batch with the given NamedSharding."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(np.asarray(x), sharding), batch)
