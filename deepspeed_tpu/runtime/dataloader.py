"""Data loading.

Equivalent of the reference's ``runtime/dataloader.py``
(``DeepSpeedDataLoader`` + ``RepeatingLoader``).  In the single-controller
model the loader yields *global* batches (every host feeds its local chips
from a globally-consistent stream); the engine shards the batch over the
``data`` mesh axis on device_put.  Works with any iterable / indexable
dataset yielding numpy arrays, dicts of arrays, or tuples.
"""
from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np

import jax


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference
    ``RepeatingLoader``)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _stack(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[i] for s in samples])
                           for i in range(len(first)))
    return np.stack(samples)


class DeepSpeedDataLoader:
    """Batches an indexable dataset into global batches of
    ``batch_size`` samples, optionally shuffled per epoch with a seeded RNG
    (deterministic across hosts — the TPU analogue of the reference's
    DistributedSampler consistency check, engine.py:434)."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 seed: int = 1234, drop_last: bool = True,
                 collate_fn=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _stack
        self.epoch = 0
        if not hasattr(dataset, "__len__") or not hasattr(dataset, "__getitem__"):
            raise TypeError("DeepSpeedDataLoader needs an indexable dataset; "
                            "wrap pure iterators with RepeatingLoader instead")

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        self.epoch += 1
        stop = (n // self.batch_size) * self.batch_size if self.drop_last else n
        for start in range(0, stop, self.batch_size):
            sel = idx[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in sel])


def shard_batch(batch, sharding) -> Any:
    """device_put every array in the batch with the given NamedSharding."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(np.asarray(x), sharding), batch)
