"""DeepSpeed Hybrid Engine: train + generate on one parameter set (RLHF).

TPU-native re-design of the reference ``runtime/hybrid_engine.py``
(``DeepSpeedHybridEngine:38``): RLHF actors alternate between experience
generation (inference) and policy updates (training) over the SAME
weights.  The reference flips ZeRO-3 modules into gathered "inference
containers" and back (``unfuse_lora``/``fuse_lora``, module-level param
copies); under GSPMD none of that machinery exists to port — the
inference step is just another jitted program consuming the live
(possibly ZeRO-sharded) parameter tree:

- ``generate()`` runs the KV-cache decode engine with a LIVE view of
  ``self.state.params`` (``param_source``) — zero host copies, no
  staging; XLA inserts whatever gathers the sharding requires and the
  serving-dtype cast happens in-graph;
- after a ``train_batch`` updates the params, the next ``generate``
  automatically sees the new weights (same buffers, no sync step);
- ``eval()`` / ``train()`` toggle bookkeeping, and generation latency /
  throughput counters mirror the reference's
  ``_generate_latency`` stats.
"""
from __future__ import annotations

import time
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + in-place generation (reference
    ``DeepSpeedHybridEngine``)."""

    def __init__(self, *args, inference_config: Optional[dict] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        assert self.module is not None, (
            "the hybrid engine needs the flax-module path (generation "
            "builds a decode-mode twin of the module)")
        self._inference_config = dict(inference_config or {})
        self._infer_engine = None
        self._training = True
        # reference latency bookkeeping (_generate_latency / _num_tokens)
        self._generate_latency = 0.0
        self._generate_tokens = 0

    # -- mode toggles (reference eval()/train() overrides) ---------------

    def train(self, mode: bool = True) -> None:
        self._training = mode

    def eval(self) -> None:
        self._training = False

    @property
    def in_training_mode(self) -> bool:
        return self._training

    # -- generation ------------------------------------------------------

    # loss-wrapper class -> (module path, logits class, param subtree key):
    # training wraps the causal-LM in a loss module; generation needs the
    # logits model underneath, whose params are the wrapper's single
    # top-level subtree
    _LOGITS_REGISTRY = {
        "GPT2LMLoss": ("deepspeed_tpu.models.gpt2", "GPT2Model",
                       "transformer"),
        "LlamaLMLoss": ("deepspeed_tpu.models.llama", "LlamaForCausalLM",
                        "lm"),
        "MixtralLMLoss": ("deepspeed_tpu.models.mixtral",
                          "MixtralForCausalLM", "lm"),
    }

    def _logits_model(self):
        """(logits module, param subtree key | None) for generation."""
        name = type(self.module).__name__
        if name in self._LOGITS_REGISTRY:
            import importlib

            mod_path, cls_name, key = self._LOGITS_REGISTRY[name]
            cls = getattr(importlib.import_module(mod_path), cls_name)
            return cls(self.module.config), key
        return self.module, None        # assume it already returns logits

    def _ensure_infer_engine(self):
        if self._infer_engine is not None:
            return self._infer_engine
        from deepspeed_tpu.inference.config import load_inference_config
        from deepspeed_tpu.inference.engine import InferenceEngine

        icfg = dict(self._inference_config)
        icfg.setdefault("dtype", self.compute_dtype.__name__)
        cfg = load_inference_config(icfg)
        model, key = self._logits_model()

        def live_params():
            p = self.state.params
            if isinstance(p, dict) and "params" in p:
                p = p["params"]
            return p[key] if key is not None else p

        self._infer_engine = InferenceEngine(
            model, cfg, topology=self.topology, param_source=live_params)
        log_dist("hybrid engine: inference twin sharing live train params",
                 ranks=[0])
        return self._infer_engine

    def generate(self, input_ids, **kwargs) -> np.ndarray:
        """Generate with the CURRENT training weights (reference
        ``DeepSpeedHybridEngine.generate``)."""
        eng = self._ensure_infer_engine()
        t0 = time.perf_counter()
        out = eng.generate(input_ids, **kwargs)
        self._generate_latency += time.perf_counter() - t0
        self._generate_tokens += int(out.size - np.asarray(input_ids).size)
        return out

    def release_inference_cache(self) -> None:
        """Drop compiled decode programs + KV cache buffers (reference
        ``release_inference_cache`` frees the inference containers)."""
        if self._infer_engine is not None:
            self._infer_engine._generate_cache.clear()
            self._infer_engine._cache_shapes.clear()

    def generate_stats(self) -> dict:
        lat = self._generate_latency
        return {"generate_seconds": lat,
                "generate_tokens": self._generate_tokens,
                "tokens_per_sec": (self._generate_tokens / lat
                                   if lat > 0 else 0.0)}


def initialize_hybrid(inference_config: Optional[dict] = None, **kwargs):
    """``deepspeed.initialize(...)`` twin returning a hybrid engine
    (the reference wires this via ``DeepSpeedConfig.hybrid_engine``);
    accepts every ``deepspeed_tpu.initialize`` argument."""
    from deepspeed_tpu.runtime.engine import initialize

    return initialize(engine_cls=DeepSpeedHybridEngine,
                      engine_kwargs={"inference_config": inference_config},
                      **kwargs)
