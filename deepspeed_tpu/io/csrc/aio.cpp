// Native async file I/O engine (DeepNVMe / csrc/aio equivalent).
//
// Re-design of the reference's deepspeed_aio_thread / py_ds_aio stack
// (csrc/aio/py_lib/deepspeed_py_io_handle.cpp, deepspeed_aio_thread.cpp,
// libaio submit path deepspeed_aio_common.cpp): a persistent pthread
// pool executes I/O jobs; each submitted job is SPLIT across the pool in
// block_size chunks (the reference's parallel single-tensor I/O),
// completion is tracked per job id, and waiters block on a condition
// variable.
//
// Each worker drives its chunk through a private io_uring (raw syscalls
// — no liburing in the image) with queue_depth block-size ops in flight,
// the TPU-host equivalent of the reference's libaio queue_depth: device
// parallelism comes from ring depth, not thread count, so one core
// saturates an NVMe.  Falls back to pread/pwrite loops when the kernel
// lacks io_uring.
//
// Write path is built for READ PARITY (the reference's ds_io target):
// files are preallocated (fallocate) before parallel chunk writes so no
// worker stalls on extent allocation, chunk boundaries are
// kDirectAlign-aligned, and O_DIRECT is honored whenever pointer+offset
// are aligned — an unaligned LENGTH splits into an aligned O_DIRECT
// main body plus a small buffered tail (disjoint byte ranges, so the
// mixed-mode coherence caveat doesn't bite), instead of silently
// degrading the whole chunk to buffered I/O like the old per-chunk
// all-or-nothing check.  A fully unaligned pointer falls back to
// buffered I/O (the reference's bounce-buffer path; callers that want
// O_DIRECT allocate via the Python-side aligned_empty()).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <linux/io_uring.h>
#include <memory>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t kDirectAlign = 4096;

struct Job {
    std::atomic<int> remaining{0};
    std::atomic<int> status{0};        // 0 ok, negative errno of first fail
};

struct Chunk {
    std::shared_ptr<Job> job;
    bool write;
    std::string path;
    char* buf;
    size_t nbytes;
    size_t offset;                      // file offset
    bool use_odirect;
};

// ---------------------------------------------------------------------------
// Minimal io_uring wrapper (raw syscalls)
// ---------------------------------------------------------------------------

int sys_io_uring_setup(unsigned entries, io_uring_params* p) {
    return (int)syscall(__NR_io_uring_setup, entries, p);
}

int sys_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                       unsigned flags) {
    return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete,
                        flags, nullptr, 0);
}

struct Ring {
    int fd = -1;
    unsigned entries = 0;
    // submission queue
    unsigned* sq_head = nullptr;
    unsigned* sq_tail = nullptr;
    unsigned* sq_mask = nullptr;
    unsigned* sq_array = nullptr;
    io_uring_sqe* sqes = nullptr;
    // completion queue
    unsigned* cq_head = nullptr;
    unsigned* cq_tail = nullptr;
    unsigned* cq_mask = nullptr;
    io_uring_cqe* cqes = nullptr;
    void* sq_ptr = nullptr;
    void* cq_ptr = nullptr;
    size_t sq_len = 0, cq_len = 0, sqe_len = 0;

    bool init(unsigned depth) {
        io_uring_params p;
        memset(&p, 0, sizeof(p));
        fd = sys_io_uring_setup(depth, &p);
        if (fd < 0) return false;
        entries = p.sq_entries;
        sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
        cq_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
        bool single_mmap = p.features & IORING_FEAT_SINGLE_MMAP;
        if (single_mmap && cq_len > sq_len) sq_len = cq_len;
        sq_ptr = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
        if (sq_ptr == MAP_FAILED) { close(); return false; }
        cq_ptr = sq_ptr;
        if (!single_mmap) {
            cq_ptr = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                          MAP_SHARED | MAP_POPULATE, fd,
                          IORING_OFF_CQ_RING);
            if (cq_ptr == MAP_FAILED) { close(); return false; }
        }
        sqe_len = p.sq_entries * sizeof(io_uring_sqe);
        sqes = (io_uring_sqe*)mmap(nullptr, sqe_len,
                                   PROT_READ | PROT_WRITE,
                                   MAP_SHARED | MAP_POPULATE, fd,
                                   IORING_OFF_SQES);
        if (sqes == MAP_FAILED) { sqes = nullptr; close(); return false; }
        auto* sq = (char*)sq_ptr;
        sq_head = (unsigned*)(sq + p.sq_off.head);
        sq_tail = (unsigned*)(sq + p.sq_off.tail);
        sq_mask = (unsigned*)(sq + p.sq_off.ring_mask);
        sq_array = (unsigned*)(sq + p.sq_off.array);
        auto* cq = (char*)cq_ptr;
        cq_head = (unsigned*)(cq + p.cq_off.head);
        cq_tail = (unsigned*)(cq + p.cq_off.tail);
        cq_mask = (unsigned*)(cq + p.cq_off.ring_mask);
        cqes = (io_uring_cqe*)(cq + p.cq_off.cqes);
        return true;
    }

    void close() {
        if (sqes) munmap(sqes, sqe_len);
        if (cq_ptr && cq_ptr != sq_ptr) munmap(cq_ptr, cq_len);
        if (sq_ptr) munmap(sq_ptr, sq_len);
        if (fd >= 0) ::close(fd);
        fd = -1; sq_ptr = cq_ptr = nullptr; sqes = nullptr;
    }

    ~Ring() { close(); }
};

struct PendingOp {
    char* buf;
    size_t len;
    size_t off;
};

// Drive one chunk through a ring: block_size ops, queue_depth in flight,
// short transfers resubmitted.  Returns 0 or -errno.  On error, stops
// submitting but DRAINS every in-flight completion before returning —
// the ring is thread_local and reused (e.g. by the O_DIRECT buffered
// retry); returning with ops in flight would let stale completions
// collide with the next run's user_data slots and touch buffers the
// caller may have freed.
int uring_rw(Ring& ring, int fd, bool write, char* buf, size_t nbytes,
             size_t file_off, size_t block, unsigned depth) {
    size_t next = 0;                    // next byte to enqueue
    size_t inflight = 0;
    int first_err = 0;
    std::vector<PendingOp> ops(ring.entries);
    std::vector<unsigned> free_slots;
    for (unsigned i = 0; i < ring.entries; ++i) free_slots.push_back(i);
    unsigned to_submit = 0;

    auto push = [&](unsigned slot, char* b, size_t len, size_t off) {
        ops[slot] = {b, len, off};
        unsigned tail = *ring.sq_tail;
        unsigned idx = tail & *ring.sq_mask;
        io_uring_sqe* sqe = &ring.sqes[idx];
        memset(sqe, 0, sizeof(*sqe));
        sqe->opcode = write ? IORING_OP_WRITE : IORING_OP_READ;
        sqe->fd = fd;
        sqe->addr = (uint64_t)b;
        sqe->len = (unsigned)len;
        sqe->off = off;
        sqe->user_data = slot;
        ring.sq_array[idx] = idx;
        __atomic_store_n(ring.sq_tail, tail + 1, __ATOMIC_RELEASE);
        ++to_submit;
        ++inflight;
    };

    while (next < nbytes || inflight > 0) {
        while (first_err == 0 && next < nbytes && !free_slots.empty() &&
               inflight < (size_t)depth) {
            size_t len = std::min(block, nbytes - next);
            unsigned slot = free_slots.back();
            free_slots.pop_back();
            push(slot, buf + next, len, file_off + next);
            next += len;
        }
        if (inflight == 0) break;
        int r = sys_io_uring_enter(ring.fd, to_submit, 1,
                                   IORING_ENTER_GETEVENTS);
        if (r < 0) {
            if (errno == EINTR) continue;
            // enter itself failed: ops submitted so far are still in
            // flight only if a previous enter succeeded; without a way
            // to reap, poison the ring so it is rebuilt next use
            if (first_err == 0) first_err = -errno;
            ring.close();
            return first_err;
        }
        to_submit = 0;
        // reap
        unsigned head = *ring.cq_head;
        unsigned tail = __atomic_load_n(ring.cq_tail, __ATOMIC_ACQUIRE);
        while (head != tail) {
            io_uring_cqe* cqe = &ring.cqes[head & *ring.cq_mask];
            unsigned slot = (unsigned)cqe->user_data;
            int res = cqe->res;
            PendingOp op = ops[slot];
            ++head;
            --inflight;
            if (res < 0) {
                if (first_err == 0) first_err = res;
                free_slots.push_back(slot);
            } else if (res == 0 && op.len > 0) {
                if (first_err == 0) first_err = -EIO;  // unexpected EOF
                free_slots.push_back(slot);
            } else if ((size_t)res < op.len && first_err == 0) {
                // short transfer: resubmit the remainder
                push(slot, op.buf + res, op.len - (size_t)res,
                     op.off + (size_t)res);
            } else {
                free_slots.push_back(slot);
            }
            __atomic_store_n(ring.cq_head, head, __ATOMIC_RELEASE);
            tail = __atomic_load_n(ring.cq_tail, __ATOMIC_ACQUIRE);
        }
    }
    return first_err;
}

struct Handle {
    int nthreads;
    size_t block_size;
    bool use_odirect;
    int backend;                        // 0 pread/pwrite, 1 io_uring
    unsigned queue_depth;
    std::vector<std::thread> workers;
    std::deque<Chunk> queue;
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    std::unordered_map<int64_t, std::shared_ptr<Job>> jobs;
    std::mutex jobs_mu;
    std::atomic<int64_t> next_id{1};
    bool stopping = false;

    // running totals (reference io_op_desc_t stats)
    std::atomic<int64_t> bytes_read{0};
    std::atomic<int64_t> bytes_written{0};
};

bool uring_available() {
    static int avail = -1;
    if (avail < 0) {
        io_uring_params p;
        memset(&p, 0, sizeof(p));
        int fd = sys_io_uring_setup(1, &p);
        if (fd >= 0) { ::close(fd); avail = 1; } else avail = 0;
    }
    return avail == 1;
}

int open_file(const std::string& path, bool write, bool odirect) {
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    if (odirect) {
        int fd = ::open(path.c_str(), flags | O_DIRECT, 0644);
        if (fd >= 0) return fd;
        // fall back to buffered I/O (reference bounce-buffer path)
    }
    return ::open(path.c_str(), flags, 0644);
}

// pread/pwrite loop over [off, off+len) of the user buffer
int plain_rw(int fd, bool write, char* buf, size_t len, size_t file_off) {
    size_t done = 0;
    while (done < len) {
        ssize_t n = write
            ? ::pwrite(fd, buf + done, len - done, (off_t)(file_off + done))
            : ::pread(fd, buf + done, len - done, (off_t)(file_off + done));
        if (n < 0) { if (errno == EINTR) continue; return -errno; }
        if (n == 0) return -EIO;            // short read / no space
        done += (size_t)n;
    }
    return 0;
}

// drive [0, len) of c through this worker's ring (falling back to the
// pread/pwrite loop where the kernel lacks usable io_uring), against fd
int engine_rw(Handle* h, int fd, const Chunk& c, char* buf, size_t len,
              size_t file_off) {
    int status = -ENOSYS;
    if (h->backend == 1) {
        thread_local Ring ring;
        thread_local unsigned ring_depth = 0;
        if (ring.fd < 0 || ring_depth != h->queue_depth) {
            ring.close();
            if (ring.init(h->queue_depth)) ring_depth = h->queue_depth;
        }
        if (ring.fd >= 0)
            status = uring_rw(ring, fd, c.write, buf, len, file_off,
                              h->block_size, h->queue_depth);
    }
    // -EINVAL / -EOPNOTSUPP: kernels 5.1-5.5 pass the io_uring_setup
    // probe but lack IORING_OP_READ/WRITE (5.6+) and fail per-op —
    // fall back to the pread/pwrite loop on the SAME fd (alignment
    // constraints are identical; O_DIRECT refusal is handled one level
    // up with a buffered reopen)
    if (status == -ENOSYS || status == -EOPNOTSUPP)
        status = plain_rw(fd, c.write, buf, len, file_off);
    return status;
}

void run_chunk(Handle* h, Chunk& c) {
    // O_DIRECT needs aligned pointer/offset/length.  Pointer+offset
    // alignment is required up front; an unaligned length only demotes
    // the TAIL (the sub-kDirectAlign remainder) to buffered I/O — the
    // aligned main body still bypasses the page cache, which is where
    // write parity with the read path comes from on NVMe.
    bool head_ok = ((uintptr_t)c.buf % kDirectAlign == 0) &&
                   (c.offset % kDirectAlign == 0);
    size_t main_len = c.nbytes & ~(kDirectAlign - 1);
    bool odirect = c.use_odirect && head_ok && main_len > 0;
    size_t tail = odirect ? c.nbytes - main_len : 0;
    int fd = open_file(c.path, c.write, odirect);
    int status = 0;
    if (fd < 0) {
        status = -errno;
    } else {
        size_t drive_len = odirect ? main_len : c.nbytes;
        status = engine_rw(h, fd, c, c.buf, drive_len, c.offset);
        if (status == -EINVAL && odirect) {
            // the fs accepted O_DIRECT at open but refuses the ops
            // (e.g. tmpfs quirks, fs-specific alignment > 4096):
            // buffered retry of the WHOLE chunk, tail included
            ::close(fd);
            tail = 0;
            fd = open_file(c.path, c.write, false);
            status = fd < 0 ? -errno
                : engine_rw(h, fd, c, c.buf, c.nbytes, c.offset);
        }
        if (status == 0 && tail > 0) {
            // buffered tail on a separate fd: its byte range is
            // disjoint from every O_DIRECT range in this job (chunk
            // boundaries are kDirectAlign-aligned), so page-cache vs
            // direct coherence never overlaps
            int tfd = open_file(c.path, c.write, false);
            status = tfd < 0 ? -errno
                : plain_rw(tfd, c.write, c.buf + main_len, tail,
                           c.offset + main_len);
            if (tfd >= 0) ::close(tfd);
        }
        if (fd >= 0) ::close(fd);
        if (status == 0) {
            if (c.write) h->bytes_written += (int64_t)c.nbytes;
            else         h->bytes_read    += (int64_t)c.nbytes;
        }
    }
    if (status != 0) {
        int expected = 0;
        c.job->status.compare_exchange_strong(expected, status);
    }
    if (c.job->remaining.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk(h->mu);
        h->done_cv.notify_all();
    }
}

void worker_loop(Handle* h) {
    for (;;) {
        Chunk c;
        {
            std::unique_lock<std::mutex> lk(h->mu);
            h->cv.wait(lk, [h] { return h->stopping || !h->queue.empty(); });
            if (h->stopping && h->queue.empty()) return;
            c = std::move(h->queue.front());
            h->queue.pop_front();
        }
        run_chunk(h, c);
    }
}

int64_t submit(Handle* h, bool write, const char* path, void* buf,
               size_t nbytes, size_t offset) {
    auto job = std::make_shared<Job>();
    // split across the pool in block_size chunks, at most nthreads ways
    size_t nchunks = 1;
    if (nbytes > h->block_size) {
        nchunks = (nbytes + h->block_size - 1) / h->block_size;
        if (nchunks > (size_t)h->nthreads) nchunks = (size_t)h->nthreads;
    }
    size_t per = (nbytes + nchunks - 1) / nchunks;
    // O_DIRECT needs kDirectAlign-aligned chunk boundaries (offsets are
    // base + k*per, so aligning per keeps every non-tail chunk eligible
    // for the direct path, not just 512-sector-aligned ones)
    if (h->use_odirect && per % kDirectAlign)
        per += kDirectAlign - per % kDirectAlign;
    std::vector<Chunk> chunks;
    for (size_t off = 0; off < nbytes; off += per) {
        Chunk c;
        c.job = job;
        c.write = write;
        c.path = path;
        c.buf = (char*)buf + off;
        c.nbytes = std::min(per, nbytes - off);
        c.offset = offset + off;
        c.use_odirect = h->use_odirect;
        chunks.push_back(std::move(c));
    }
    job->remaining = (int)chunks.size();
    int64_t id = h->next_id.fetch_add(1);
    {
        std::lock_guard<std::mutex> lk(h->jobs_mu);
        h->jobs[id] = job;
    }
    {
        std::lock_guard<std::mutex> lk(h->mu);
        for (auto& c : chunks) h->queue.push_back(std::move(c));
    }
    h->cv.notify_all();
    return id;
}

std::shared_ptr<Job> find_job(Handle* h, int64_t id) {
    std::lock_guard<std::mutex> lk(h->jobs_mu);
    auto it = h->jobs.find(id);
    return it == h->jobs.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

// backend: 0 = pread/pwrite thread pool, 1 = io_uring, -1 = auto
// (io_uring when the kernel supports it)
void* aio_create2(int num_threads, int64_t block_size, int use_odirect,
                  int backend, int queue_depth) {
    auto* h = new Handle();
    h->nthreads = num_threads > 0 ? num_threads : 1;
    h->block_size = block_size > 0 ? (size_t)block_size : (1u << 20);
    h->use_odirect = use_odirect != 0;
    if (backend < 0) backend = uring_available() ? 1 : 0;
    if (backend == 1 && !uring_available()) backend = 0;
    h->backend = backend;
    h->queue_depth = queue_depth > 0 ? (unsigned)queue_depth : 64u;
    for (int i = 0; i < h->nthreads; ++i)
        h->workers.emplace_back(worker_loop, h);
    return h;
}

void* aio_create(int num_threads, int64_t block_size, int use_odirect) {
    return aio_create2(num_threads, block_size, use_odirect, -1, 64);
}

int aio_backend(void* hp) { return ((Handle*)hp)->backend; }

void aio_destroy(void* hp) {
    auto* h = (Handle*)hp;
    {
        std::lock_guard<std::mutex> lk(h->mu);
        h->stopping = true;
    }
    h->cv.notify_all();
    for (auto& t : h->workers) t.join();
    delete h;
}

int64_t aio_submit_read(void* hp, const char* path, void* buf,
                        int64_t nbytes, int64_t offset) {
    return submit((Handle*)hp, false, path, buf, (size_t)nbytes,
                  (size_t)offset);
}

int64_t aio_submit_write(void* hp, const char* path, void* buf,
                         int64_t nbytes, int64_t offset) {
    return submit((Handle*)hp, true, path, buf, (size_t)nbytes,
                  (size_t)offset);
}

// -1 = still pending; otherwise job status (0 ok / -errno)
int aio_poll(void* hp, int64_t id) {
    auto* h = (Handle*)hp;
    auto job = find_job(h, id);
    if (!job) return -EINVAL;
    if (job->remaining.load() > 0) return -1;
    return job->status.load();
}

int aio_wait(void* hp, int64_t id) {
    auto* h = (Handle*)hp;
    auto job = find_job(h, id);
    if (!job) return -EINVAL;
    {
        std::unique_lock<std::mutex> lk(h->mu);
        h->done_cv.wait(lk, [&] { return job->remaining.load() == 0; });
    }
    int st = job->status.load();
    {
        std::lock_guard<std::mutex> lk(h->jobs_mu);
        h->jobs.erase(id);
    }
    return st;
}

int aio_pread(void* hp, const char* path, void* buf, int64_t nbytes,
              int64_t offset) {
    return aio_wait(hp, aio_submit_read(hp, path, buf, nbytes, offset));
}

int aio_pwrite(void* hp, const char* path, void* buf, int64_t nbytes,
               int64_t offset) {
    return aio_wait(hp, aio_submit_write(hp, path, buf, nbytes, offset));
}

int64_t aio_bytes_read(void* hp) { return ((Handle*)hp)->bytes_read.load(); }
int64_t aio_bytes_written(void* hp) {
    return ((Handle*)hp)->bytes_written.load();
}
int64_t aio_file_size(const char* path) {
    struct stat st;
    if (::stat(path, &st) != 0) return -errno;
    return (int64_t)st.st_size;
}

// Extend-only preallocation: size the file AND reserve its extents
// before parallel chunk writes, so no worker stalls inside the fs
// allocator mid-stream (the reference preallocates its swap buffers the
// same way).  Never shrinks.  Returns 0 or -errno.
int aio_prealloc(const char* path, int64_t size) {
    int fd = ::open(path, O_WRONLY | O_CREAT, 0644);
    if (fd < 0) return -errno;
    int status = 0;
    struct stat st;
    if (::fstat(fd, &st) != 0) {
        status = -errno;
    } else if (st.st_size < size) {
        int rc = ::posix_fallocate(fd, 0, size);
        // fs without fallocate support (e.g. some overlay/tmpfs): a
        // plain size extension still gives parallel writers a stable
        // file length (extents then allocate lazily)
        if (rc != 0 && ::ftruncate(fd, size) != 0) status = -errno;
    }
    ::close(fd);
    return status;
}

}  // extern "C"
