// Native async file I/O engine (DeepNVMe / csrc/aio equivalent).
//
// Re-design of the reference's deepspeed_aio_thread / py_ds_aio stack
// (csrc/aio/py_lib/deepspeed_py_io_handle.cpp, deepspeed_aio_thread.cpp):
// a persistent pthread pool executes pread/pwrite jobs; each submitted
// job is SPLIT across the pool in block_size chunks (the reference's
// parallel single-tensor I/O), completion is tracked per job id, and
// waiters block on a condition variable.  O_DIRECT is honored when the
// caller guarantees alignment (flag falls back to buffered I/O if the
// open fails, matching the reference's bounce-buffer fallback).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Job {
    std::atomic<int> remaining{0};
    std::atomic<int> status{0};        // 0 ok, negative errno of first fail
};

struct Chunk {
    std::shared_ptr<Job> job;
    bool write;
    std::string path;
    char* buf;
    size_t nbytes;
    size_t offset;                      // file offset
    bool use_odirect;
};

struct Handle {
    int nthreads;
    size_t block_size;
    bool use_odirect;
    std::vector<std::thread> workers;
    std::deque<Chunk> queue;
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    std::unordered_map<int64_t, std::shared_ptr<Job>> jobs;
    std::mutex jobs_mu;
    std::atomic<int64_t> next_id{1};
    bool stopping = false;

    // running totals (reference io_op_desc_t stats)
    std::atomic<int64_t> bytes_read{0};
    std::atomic<int64_t> bytes_written{0};
};

int open_file(const std::string& path, bool write, bool odirect) {
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    if (odirect) {
        int fd = ::open(path.c_str(), flags | O_DIRECT, 0644);
        if (fd >= 0) return fd;
        // fall back to buffered I/O (reference bounce-buffer path)
    }
    return ::open(path.c_str(), flags, 0644);
}

void run_chunk(Handle* h, Chunk& c) {
    int fd = open_file(c.path, c.write, c.use_odirect);
    int status = 0;
    if (fd < 0) {
        status = -errno;
    } else {
        size_t done = 0;
        while (done < c.nbytes) {
            ssize_t n = c.write
                ? ::pwrite(fd, c.buf + done, c.nbytes - done,
                           (off_t)(c.offset + done))
                : ::pread(fd, c.buf + done, c.nbytes - done,
                          (off_t)(c.offset + done));
            if (n < 0) { status = -errno; break; }
            if (n == 0) { status = -EIO; break; }   // short read
            done += (size_t)n;
        }
        ::close(fd);
        if (status == 0) {
            if (c.write) h->bytes_written += (int64_t)c.nbytes;
            else         h->bytes_read    += (int64_t)c.nbytes;
        }
    }
    if (status != 0) {
        int expected = 0;
        c.job->status.compare_exchange_strong(expected, status);
    }
    if (c.job->remaining.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk(h->mu);
        h->done_cv.notify_all();
    }
}

void worker_loop(Handle* h) {
    for (;;) {
        Chunk c;
        {
            std::unique_lock<std::mutex> lk(h->mu);
            h->cv.wait(lk, [h] { return h->stopping || !h->queue.empty(); });
            if (h->stopping && h->queue.empty()) return;
            c = std::move(h->queue.front());
            h->queue.pop_front();
        }
        run_chunk(h, c);
    }
}

int64_t submit(Handle* h, bool write, const char* path, void* buf,
               size_t nbytes, size_t offset) {
    auto job = std::make_shared<Job>();
    // split across the pool in block_size chunks, at most nthreads ways
    size_t nchunks = 1;
    if (nbytes > h->block_size) {
        nchunks = (nbytes + h->block_size - 1) / h->block_size;
        if (nchunks > (size_t)h->nthreads) nchunks = (size_t)h->nthreads;
    }
    size_t per = (nbytes + nchunks - 1) / nchunks;
    // O_DIRECT needs 512-aligned chunk boundaries
    if (h->use_odirect && per % 512) per += 512 - per % 512;
    std::vector<Chunk> chunks;
    for (size_t off = 0; off < nbytes; off += per) {
        Chunk c;
        c.job = job;
        c.write = write;
        c.path = path;
        c.buf = (char*)buf + off;
        c.nbytes = std::min(per, nbytes - off);
        c.offset = offset + off;
        c.use_odirect = h->use_odirect;
        chunks.push_back(std::move(c));
    }
    job->remaining = (int)chunks.size();
    int64_t id = h->next_id.fetch_add(1);
    {
        std::lock_guard<std::mutex> lk(h->jobs_mu);
        h->jobs[id] = job;
    }
    {
        std::lock_guard<std::mutex> lk(h->mu);
        for (auto& c : chunks) h->queue.push_back(std::move(c));
    }
    h->cv.notify_all();
    return id;
}

std::shared_ptr<Job> find_job(Handle* h, int64_t id) {
    std::lock_guard<std::mutex> lk(h->jobs_mu);
    auto it = h->jobs.find(id);
    return it == h->jobs.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

void* aio_create(int num_threads, int64_t block_size, int use_odirect) {
    auto* h = new Handle();
    h->nthreads = num_threads > 0 ? num_threads : 1;
    h->block_size = block_size > 0 ? (size_t)block_size : (1u << 20);
    h->use_odirect = use_odirect != 0;
    for (int i = 0; i < h->nthreads; ++i)
        h->workers.emplace_back(worker_loop, h);
    return h;
}

void aio_destroy(void* hp) {
    auto* h = (Handle*)hp;
    {
        std::lock_guard<std::mutex> lk(h->mu);
        h->stopping = true;
    }
    h->cv.notify_all();
    for (auto& t : h->workers) t.join();
    delete h;
}

int64_t aio_submit_read(void* hp, const char* path, void* buf,
                        int64_t nbytes, int64_t offset) {
    return submit((Handle*)hp, false, path, buf, (size_t)nbytes,
                  (size_t)offset);
}

int64_t aio_submit_write(void* hp, const char* path, void* buf,
                         int64_t nbytes, int64_t offset) {
    return submit((Handle*)hp, true, path, buf, (size_t)nbytes,
                  (size_t)offset);
}

// -1 = still pending; otherwise job status (0 ok / -errno)
int aio_poll(void* hp, int64_t id) {
    auto* h = (Handle*)hp;
    auto job = find_job(h, id);
    if (!job) return -EINVAL;
    if (job->remaining.load() > 0) return -1;
    return job->status.load();
}

int aio_wait(void* hp, int64_t id) {
    auto* h = (Handle*)hp;
    auto job = find_job(h, id);
    if (!job) return -EINVAL;
    {
        std::unique_lock<std::mutex> lk(h->mu);
        h->done_cv.wait(lk, [&] { return job->remaining.load() == 0; });
    }
    int st = job->status.load();
    {
        std::lock_guard<std::mutex> lk(h->jobs_mu);
        h->jobs.erase(id);
    }
    return st;
}

int aio_pread(void* hp, const char* path, void* buf, int64_t nbytes,
              int64_t offset) {
    return aio_wait(hp, aio_submit_read(hp, path, buf, nbytes, offset));
}

int aio_pwrite(void* hp, const char* path, void* buf, int64_t nbytes,
               int64_t offset) {
    return aio_wait(hp, aio_submit_write(hp, path, buf, nbytes, offset));
}

int64_t aio_bytes_read(void* hp) { return ((Handle*)hp)->bytes_read.load(); }
int64_t aio_bytes_written(void* hp) {
    return ((Handle*)hp)->bytes_written.load();
}
int64_t aio_file_size(const char* path) {
    struct stat st;
    if (::stat(path, &st) != 0) return -errno;
    return (int64_t)st.st_size;
}

}  // extern "C"
