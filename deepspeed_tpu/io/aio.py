"""ctypes bindings + JIT builder for the native async I/O engine.

Python face of ``csrc/aio.cpp`` — the DeepNVMe equivalent (reference
``ops/op_builder/async_io.py AsyncIOBuilder`` + ``csrc/aio/py_lib``
``deepspeed_py_io_handle``).  The reference JIT-compiles CUDA/C++ ops at
first use through its op_builder; the same pattern here: ``g++`` builds
the shared library on first import (cached next to the source, rebuilt
when the source is newer), and ``ctypes`` provides the bindings — no
pybind11 in this image.

API mirrors the reference handle surface::

    h = aio_handle(block_size=1<<20, queue_depth=..., thread_count=8)
    h.sync_pwrite(array, path)          # parallel chunked pwrite
    h.sync_pread(array, path)
    op = h.async_pwrite(array, path)    # returns op id immediately
    h.wait(op)                          # 0 on success

Buffers are numpy arrays (or anything exposing the buffer protocol);
``pinned`` host memory is not a TPU-visible concept — host RAM is the
staging tier, jax handles H2D.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "csrc", "aio.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "csrc", "libdstpu_aio.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class AsyncIOBuilder:
    """Reference ``AsyncIOBuilder`` shape: ``.load()`` returns the bound
    module (building it first if needed), ``.is_compatible()`` reports
    whether a toolchain exists."""

    NAME = "async_io"

    def is_compatible(self) -> bool:
        from shutil import which

        return which("g++") is not None

    def load(self):
        _ensure_built()
        import deepspeed_tpu.io.aio as mod

        return mod


def _ensure_built() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        stale = (not os.path.exists(_LIB) or
                 os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        if stale:
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread",
                   "-std=c++17", _SRC, "-o", _LIB + ".tmp"]
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(_LIB + ".tmp", _LIB)
        lib = ctypes.CDLL(_LIB)
        lib.aio_create.restype = ctypes.c_void_p
        lib.aio_create.argtypes = [ctypes.c_int, ctypes.c_int64,
                                   ctypes.c_int]
        lib.aio_create2.restype = ctypes.c_void_p
        lib.aio_create2.argtypes = [ctypes.c_int, ctypes.c_int64,
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int]
        lib.aio_backend.restype = ctypes.c_int
        lib.aio_backend.argtypes = [ctypes.c_void_p]
        lib.aio_destroy.argtypes = [ctypes.c_void_p]
        for fn in (lib.aio_submit_read, lib.aio_submit_write):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        for fn in (lib.aio_pread, lib.aio_pwrite):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        lib.aio_wait.restype = ctypes.c_int
        lib.aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.aio_poll.restype = ctypes.c_int
        lib.aio_poll.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        for fn in (lib.aio_bytes_read, lib.aio_bytes_written):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p]
        lib.aio_file_size.restype = ctypes.c_int64
        lib.aio_file_size.argtypes = [ctypes.c_char_p]
        lib.aio_prealloc.restype = ctypes.c_int
        lib.aio_prealloc.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        _lib = lib
        return lib


_DIRECT_ALIGN = 4096


def aligned_empty(n: int, dtype=np.uint8) -> np.ndarray:
    """Uninitialized 1-D array of ``n`` elements whose data pointer is
    kDirectAlign(4096)-aligned — the O_DIRECT eligibility requirement
    the native engine checks per chunk.  numpy's allocator only
    guarantees 16-byte alignment, so buffers meant for O_DIRECT
    streaming (swap bucket buffers, bench buffers) come from here."""
    dt = np.dtype(dtype)
    raw = np.empty(n * dt.itemsize + _DIRECT_ALIGN, np.uint8)
    off = (-raw.ctypes.data) % _DIRECT_ALIGN
    return raw[off:off + n * dt.itemsize].view(dt)


def _buf_ptr(arr: np.ndarray):
    assert arr.flags["C_CONTIGUOUS"], "aio buffers must be contiguous"
    return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes


class aio_handle:
    """Reference ``aio_handle`` surface (``deepspeed_py_io_handle.cpp``):
    thread-pooled, chunk-parallel file I/O with sync and async calls."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 128,
                 single_submit: bool = False, overlap_events: bool = True,
                 thread_count: int = 8, use_odirect: bool = False,
                 backend: str = "auto"):
        """``queue_depth``: io_uring ops in flight per worker (the
        reference's libaio queue_depth — device parallelism comes from
        ring depth, not threads).  ``backend``: "auto" | "uring" |
        "threadpool"."""
        del single_submit, overlap_events   # libaio-era knobs
        self._lib = _ensure_built()
        bk = {"auto": -1, "uring": 1, "threadpool": 0}[backend]
        self._h = self._lib.aio_create2(int(thread_count), int(block_size),
                                        int(bool(use_odirect)), bk,
                                        int(queue_depth))
        self.block_size = block_size
        self.thread_count = thread_count
        self.queue_depth = queue_depth
        self.backend = ("uring" if self._lib.aio_backend(self._h) == 1
                        else "threadpool")
        # keep submitted buffers alive until wait() (the C side reads them)
        self._live: dict = {}

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.aio_destroy(self._h)
                self._h = None
        except Exception:
            pass

    # -- sync ----------------------------------------------------------

    def sync_pread(self, buffer: np.ndarray, path: str,
                   offset: int = 0) -> int:
        ptr, n = _buf_ptr(buffer)
        st = self._lib.aio_pread(self._h, path.encode(), ptr, n, offset)
        if st != 0:
            raise OSError(-st, os.strerror(-st), path)
        return n

    def sync_pwrite(self, buffer: np.ndarray, path: str,
                    offset: int = 0) -> int:
        ptr, n = _buf_ptr(buffer)
        _pretruncate(path, offset + n, exact=offset == 0)
        st = self._lib.aio_pwrite(self._h, path.encode(), ptr, n, offset)
        if st != 0:
            raise OSError(-st, os.strerror(-st), path)
        return n

    # -- async ---------------------------------------------------------

    def async_pread(self, buffer: np.ndarray, path: str,
                    offset: int = 0) -> int:
        ptr, n = _buf_ptr(buffer)
        op = self._lib.aio_submit_read(self._h, path.encode(), ptr, n,
                                       offset)
        self._live[op] = buffer
        return op

    def async_pwrite(self, buffer: np.ndarray, path: str,
                     offset: int = 0, _truncate: bool = True) -> int:
        ptr, n = _buf_ptr(buffer)
        if _truncate:
            # extend-only: concurrent multi-part writes to one file must
            # size it up-front (see checkpoint writer) — a shrink here
            # could cut an in-flight higher-offset chunk
            _pretruncate(path, offset + n, exact=False)
        op = self._lib.aio_submit_write(self._h, path.encode(), ptr, n,
                                        offset)
        self._live[op] = buffer
        return op

    def poll(self, op: int) -> Optional[int]:
        """None while pending, else final status (0 = ok)."""
        st = self._lib.aio_poll(self._h, op)
        return None if st == -1 else st

    def wait(self, op: int) -> int:
        st = self._lib.aio_wait(self._h, op)
        self._live.pop(op, None)
        if st != 0:
            raise OSError(-st, os.strerror(-st))
        return st

    # -- stats ----------------------------------------------------------

    def bytes_read(self) -> int:
        return self._lib.aio_bytes_read(self._h)

    def bytes_written(self) -> int:
        return self._lib.aio_bytes_written(self._h)


def _pretruncate(path: str, size: int, exact: bool = True) -> None:
    """Size the file before parallel chunk writes (chunk opens use
    O_CREAT without O_TRUNC — truncating per-chunk would race).
    ``exact=False`` only ever EXTENDS, safe around in-flight writes.
    Extensions go through the native ``aio_prealloc`` (fallocate), so
    the extents exist before the parallel writers hit them — extent
    allocation mid-stream is one of the two things that held the write
    path below the read path (the other is the page cache; O_DIRECT)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "ab"):
        pass
    cur = os.path.getsize(path)
    if cur < size:
        st = _ensure_built().aio_prealloc(path.encode(), size)
        if st != 0:
            raise OSError(-st, os.strerror(-st), path)
    elif cur > size and exact:
        os.truncate(path, size)


def file_size(path: str) -> int:
    lib = _ensure_built()
    n = lib.aio_file_size(path.encode())
    if n < 0:
        raise OSError(-n, os.strerror(-n), path)
    return n
