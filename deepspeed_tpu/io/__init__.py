from deepspeed_tpu.io.aio import AsyncIOBuilder, aio_handle

__all__ = ["AsyncIOBuilder", "aio_handle"]
