from deepspeed_tpu.io.aio import AsyncIOBuilder, aio_handle


def io_sweep(*args, **kwargs):
    """See :func:`deepspeed_tpu.io.bench.sweep` (lazy import keeps
    ``python -m deepspeed_tpu.io.bench`` runpy-clean)."""
    from deepspeed_tpu.io.bench import sweep

    return sweep(*args, **kwargs)


def io_tune(*args, **kwargs):
    """See :func:`deepspeed_tpu.io.bench.tune`."""
    from deepspeed_tpu.io.bench import tune

    return tune(*args, **kwargs)


__all__ = ["AsyncIOBuilder", "aio_handle", "io_sweep", "io_tune"]
