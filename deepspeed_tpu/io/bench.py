"""``ds_io`` / ``ds_nvme_tune`` equivalent: benchmark + auto-tune the
native async-IO engine.

The reference ships a sweep harness for its AIO kernels
(``deepspeed/nvme/perf_run_sweep.py``, ``ds_aio_handle.py``, CLIs
``ds_io`` / ``ds_nvme_tune``) that searches (block_size, queue_depth,
io_parallel) for the storage device backing offload/checkpoint traffic.
Same idea here, sized to the TPU runtime's AIO engine (``io/aio.py``):
sweep (block_size, thread_count), measure sync read/write GB/s against a
target directory, and report the best configuration — the values to put
in the config's ``aio.block_size`` / ``aio.thread_count`` knobs (NVMe
optimizer swap, checkpoint writer).

CLI::

    python -m deepspeed_tpu.io.bench --dir /mnt/nvme --size-mb 256
    python -m deepspeed_tpu.io.bench --dir /mnt/nvme --tune

Each line of output is one sweep point; ``--tune`` ends with a JSON line
of the winning config (machine-readable, like the reference's generated
aio param).
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

DEFAULT_BLOCK_SIZES = [1 << 20, 8 << 20]          # 1M, 8M
DEFAULT_THREAD_COUNTS = [1, 4]
# io_uring ring depth: the reference's libaio queue_depth axis — on
# NVMe this is the lever that matters, not thread count
DEFAULT_QUEUE_DEPTHS = [32, 128]
DEFAULT_ODIRECT = [False, True]


def _sync_and_evict(path: str) -> None:
    """fsync + best-effort page-cache eviction so the subsequent read hits
    the device rather than memory (the reference drops the cache via
    /proc/sys/vm — needs root; POSIX_FADV_DONTNEED is the portable part)."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
            if hasattr(os, "posix_fadvise"):
                os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
        finally:
            os.close(fd)
    except OSError:
        pass


def bench_point(directory: str, size_bytes: int, block_size: int,
                thread_count: int, loops: int = 3,
                queue_depth: int = 64, use_odirect: bool = False
                ) -> Tuple[float, float]:
    """(read_gbps, write_gbps) for one (block_size, thread_count,
    queue_depth, odirect) point.

    Write timing includes the fsync (device flush), and the page cache is
    evicted (best effort) before each read so both directions measure
    storage, not memory.  Residual cache effects remain possible on
    filesystems where fadvise is a no-op — run with a ``--size-mb`` well
    above RAM for authoritative device numbers, as with the reference's
    ``ds_io``.
    """
    from deepspeed_tpu.io.aio import aio_handle

    if loops < 1:
        raise ValueError(f"loops must be >= 1, got {loops}")
    h = aio_handle(block_size=block_size, thread_count=thread_count,
                   queue_depth=queue_depth, use_odirect=use_odirect)
    path = os.path.join(directory, f"dstpu_io_bench_{os.getpid()}.bin")
    buf = np.random.default_rng(0).integers(
        0, 255, size_bytes, dtype=np.uint8)
    rbuf = np.empty(size_bytes, np.uint8)
    try:
        wt = rt = 0.0
        for _ in range(loops):
            t0 = time.perf_counter()
            h.sync_pwrite(buf, path)
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            wt += time.perf_counter() - t0
            _sync_and_evict(path)
            t0 = time.perf_counter()
            h.sync_pread(rbuf, path)
            rt += time.perf_counter() - t0
        assert rbuf[:4096].tobytes() == buf[:4096].tobytes(), \
            "read-back mismatch"
        gb = size_bytes * loops / 1e9
        return gb / rt, gb / wt
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


def raw_control(directory: str, size_bytes: int,
                block: int = 8 << 20) -> Tuple[float, float]:
    """Device-roofline CONTROL: single-stream O_DIRECT sequential
    pwritev/preadv with a page-aligned buffer and NO ring engine, no
    threads — what the raw device gives the dumbest possible writer.
    Engine numbers near this are device-bound, not engine-bound;
    an engine well below it has submission overhead to claim back.
    Returns (read_gbps, write_gbps); (0, 0) when O_DIRECT is
    unsupported on the target filesystem (e.g. tmpfs)."""
    import mmap

    path = os.path.join(directory, f"dstpu_io_ctrl_{os.getpid()}.bin")
    buf = mmap.mmap(-1, block)                      # page-aligned
    buf.write(os.urandom(min(block, 1 << 16)))
    n_blocks = max(1, size_bytes // block)
    try:
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC |
                         os.O_DIRECT)
        except OSError:
            return 0.0, 0.0
        try:
            t0 = time.perf_counter()
            for i in range(n_blocks):
                os.pwritev(fd, [buf], i * block)
            os.fsync(fd)
            wt = time.perf_counter() - t0
        finally:
            os.close(fd)
        _sync_and_evict(path)
        fd = os.open(path, os.O_RDONLY | os.O_DIRECT)
        try:
            t0 = time.perf_counter()
            for i in range(n_blocks):
                os.preadv(fd, [buf], i * block)
            rt = time.perf_counter() - t0
        finally:
            os.close(fd)
        gb = n_blocks * block / 1e9
        return gb / rt, gb / wt
    finally:
        buf.close()
        try:
            os.remove(path)
        except OSError:
            pass


def sweep(directory: str, size_bytes: int,
          block_sizes: Optional[List[int]] = None,
          thread_counts: Optional[List[int]] = None,
          queue_depths: Optional[List[int]] = None,
          odirect: Optional[List[bool]] = None,
          loops: int = 3, verbose: bool = True,
          json_lines: bool = False) -> List[Dict]:
    """Full sweep; one record per point, best combined read+write GB/s
    first (the swap workload is symmetric: every step reads AND writes
    the full moment set).  ``json_lines`` prints each point as one JSON
    line instead of the human table (``--sweep`` CLI mode — pipe into
    jq / a plotting script)."""
    results = []
    for bs in (block_sizes or DEFAULT_BLOCK_SIZES):
        for tc in (thread_counts or DEFAULT_THREAD_COUNTS):
            for qd in (queue_depths or DEFAULT_QUEUE_DEPTHS):
                for od in (DEFAULT_ODIRECT if odirect is None else odirect):
                    read_gbps, write_gbps = bench_point(
                        directory, size_bytes, bs, tc, loops=loops,
                        queue_depth=qd, use_odirect=od)
                    rec = {"block_size": bs, "thread_count": tc,
                           "queue_depth": qd, "use_odirect": od,
                           "read_gbps": round(read_gbps, 3),
                           "write_gbps": round(write_gbps, 3)}
                    results.append(rec)
                    if json_lines:
                        print(json.dumps(rec), flush=True)
                    elif verbose:
                        print(f"block={bs >> 20}M threads={tc:<3d} "
                              f"qd={qd:<4d} odirect={int(od)} "
                              f"read={read_gbps:6.2f} GB/s "
                              f"write={write_gbps:6.2f} GB/s", flush=True)
    return sorted(results, key=lambda r: -(r["read_gbps"] +
                                           r["write_gbps"]))


def best_write_config(results: List[Dict]) -> Dict:
    """The sweep point with the highest WRITE throughput, shaped like
    the ``aio`` config subtree — the write side is the historically
    deficient direction (VERDICT r5: 0.55 vs 1.91 GB/s), so the write
    winner is what picks the swap stream's defaults."""
    best = max(results, key=lambda r: r["write_gbps"])
    return {"write_gbps": best["write_gbps"],
            "read_gbps": best["read_gbps"],
            "config": {"aio": {"block_size": best["block_size"],
                               "thread_count": best["thread_count"],
                               "queue_depth": best["queue_depth"],
                               "use_odirect": best["use_odirect"]}}}


def tune(directory: str, size_bytes: int = 256 << 20,
         block_sizes: Optional[List[int]] = None,
         thread_counts: Optional[List[int]] = None,
         queue_depths: Optional[List[int]] = None,
         odirect: Optional[List[bool]] = None,
         loops: int = 3, verbose: bool = True) -> Dict:
    """``ds_nvme_tune`` equivalent: run the sweep, return the winning
    config.  ``best["config"]`` is shaped exactly like the DeepSpeed
    config subtree it belongs in (``AioConfig``): paste it as the
    ``aio`` section."""
    results = sweep(directory, size_bytes, block_sizes=block_sizes,
                    thread_counts=thread_counts,
                    queue_depths=queue_depths, odirect=odirect,
                    loops=loops, verbose=verbose)
    best = dict(results[0])
    best["config"] = {"aio": {"block_size": best["block_size"],
                              "thread_count": best["thread_count"],
                              "queue_depth": best["queue_depth"],
                              "use_odirect": best["use_odirect"]}}
    return best


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="benchmark / tune the native async-IO engine")
    p.add_argument("--dir", default="/tmp", help="target directory "
                   "(point at the NVMe mount you plan to offload to)")
    p.add_argument("--size-mb", type=int, default=256,
                   help="file size per point")
    def _positive(v):
        v = int(v)
        if v < 1:
            raise argparse.ArgumentTypeError("must be >= 1")
        return v

    p.add_argument("--loops", type=_positive, default=3)
    p.add_argument("--block-sizes", type=int, nargs="*",
                   help="block sizes in bytes")
    p.add_argument("--threads", type=int, nargs="*",
                   help="thread counts")
    p.add_argument("--queue-depths", type=int, nargs="*",
                   help="io_uring ring depths")
    p.add_argument("--odirect", type=int, nargs="*", choices=[0, 1],
                   help="O_DIRECT settings to sweep (0/1)")
    p.add_argument("--tune", action="store_true",
                   help="print the winning config as a JSON line")
    p.add_argument("--sweep", action="store_true",
                   help="grid queue_depth x block_size x thread_count "
                        "(x odirect) for read AND write, one JSON line "
                        "per point, ending with the best-write config "
                        "(the ds_nvme_tune-style tuning pass that picks "
                        "the swap stream's aio defaults)")
    args = p.parse_args(argv)
    size = args.size_mb << 20
    od = None if args.odirect is None else [bool(v) for v in args.odirect]
    if args.sweep:
        results = sweep(args.dir, size, block_sizes=args.block_sizes,
                        thread_counts=args.threads,
                        queue_depths=args.queue_depths, odirect=od,
                        loops=args.loops, json_lines=True)
        print(json.dumps({"best_write": best_write_config(results)}))
    elif args.tune:
        best = tune(args.dir, size, block_sizes=args.block_sizes,
                    thread_counts=args.threads,
                    queue_depths=args.queue_depths, odirect=od,
                    loops=args.loops)
        print(json.dumps(best))
    else:
        sweep(args.dir, size, block_sizes=args.block_sizes,
              thread_counts=args.threads, queue_depths=args.queue_depths,
              odirect=od, loops=args.loops)


if __name__ == "__main__":
    main()
