"""DeepSpeed-TPU: TPU-native large-model training & inference framework.

Brand-new JAX/XLA/Pallas implementation of the capabilities of the reference
DeepSpeed (jpli02/DeepSpeed v0.16.4); see SURVEY.md for the component map.
The top-level API mirrors the reference's ``deepspeed/__init__.py``
(``initialize`` at :69, ``init_inference`` at :291) in spirit while being
functional underneath.
"""

__version__ = "0.1.0"

from deepspeed_tpu.utils.logging import logger, log_dist  # noqa: F401
from deepspeed_tpu.config import DeepSpeedConfig, load_config  # noqa: F401
import deepspeed_tpu.comm as comm  # noqa: F401


def initialize(*args, **kwargs):
    """Create a training engine (reference ``deepspeed.initialize``)."""
    from deepspeed_tpu.runtime.engine import initialize as _init

    return _init(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Create an inference engine (reference ``deepspeed.init_inference``)."""
    from deepspeed_tpu.inference.engine import init_inference as _init

    return _init(*args, **kwargs)


def initialize_hybrid(*args, **kwargs):
    """Create a hybrid train+generate engine for RLHF (reference
    ``DeepSpeedHybridEngine``, ``runtime/hybrid_engine.py:38``)."""
    from deepspeed_tpu.runtime.hybrid_engine import \
        initialize_hybrid as _init

    return _init(*args, **kwargs)
