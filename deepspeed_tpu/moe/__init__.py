from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import (top1gating, top2gating, topkgating,
                                           moe_combine, moe_dispatch)

__all__ = ["MoE", "top1gating", "top2gating", "topkgating", "moe_combine",
           "moe_dispatch"]
