"""Mixture-of-Experts layer.

TPU-native re-design of the reference MoE stack (``deepspeed/moe/layer.py:17
MoE``, ``sharded_moe.py:533 MOELayer``, ``TopKGate:449``): the reference
builds per-rank expert modules and issues explicit all-to-alls
(``_AllToAll:96``) between gate, experts, and combine; here the experts are
ONE stacked parameter tensor ``[E, ...]`` whose leading axis is annotated
onto the ``expert`` mesh axis, dispatch/combine are einsums against the
gating tensors, and XLA/GSPMD inserts the all-to-alls when the ``[E, C, M]``
dispatched activations are sharding-constrained onto the expert axis — the
same wire traffic, riding ICI, without hand-rolled comm.

Expert-parallel composition mirrors ``groups.py:236
_create_expert_and_data_parallel``: the ``expert`` mesh axis carries both
the expert shards and (being a ZeRO axis) a slice of the data batch, so
ep_size experts x dp replicas works out of the box; MoE-aware ZeRO
(``stage_1_and_2.py:616 _configure_moe_settings``) falls out of the
sharding-plan composition — expert params keep their ``expert`` axis and
ZeRO claims a *different* dim.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.sharded_moe import (moe_combine, moe_combine_gather,
                                           moe_dispatch, moe_dispatch_gather,
                                           routing_plan, sorted_combine,
                                           sorted_dispatch, topkgating)
from deepspeed_tpu.utils.sharding import maybe_constrain as _maybe_constrain

EXPERT_AXIS = "expert"


class MoE(nn.Module):
    """Top-k routed MoE FFN: gate -> dispatch -> experts -> combine.

    Returns ``(y, l_aux)``; the caller plumbs ``l_aux`` into the training
    loss (reference stores it on the layer and the engine collects it).
    """

    hidden_size: int
    num_experts: int
    intermediate_size: int
    k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    activation: str = "swiglu"             # "swiglu" (Mixtral) | "gelu"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    expert_parallel: bool = True           # annotate the expert mesh axis
    tensor_parallel: bool = False          # shard expert FFN over `tensor`
    noisy_gate_policy: Optional[str] = None  # None | "Jitter"
    # "sorted": expert-sorted row gathers feeding the dense batched FFN —
    # linear in token count, no [G, E, C] one-hots, no scatter anywhere
    # (fwd or bwd); the TPU equivalent of the reference's grouped MoE
    # GEMM (cutlass_ops/moe_gemm).  "einsum" is the reference's dense
    # one-hot dispatch: G*E*C*M MACs each way (QUADRATIC in G since
    # C ~ kG/E) but expressed purely as einsums, which GSPMD knows how
    # to shard over the expert axis — required for expert-parallel
    # meshes, and the parity oracle.  "gather" is the row-scatter path:
    # measured ~20x slower on v5e (TPU scatter lowering), CPU/debug only.
    # "auto" (default) resolves to "sorted" only when the installed
    # topology is single-device (or absent): the plan's global argsort and
    # data-dependent gathers defeat GSPMD partitioning of ANY sharded
    # token or expert axis, forcing per-layer all-gathers on multi-chip
    # meshes — dp-only meshes included, not just expert-parallel ones.
    dispatch_impl: str = "auto"

    def _resolve_dispatch(self) -> str:
        if self.dispatch_impl != "auto":
            return self.dispatch_impl
        import deepspeed_tpu.comm as dist

        topo = dist.peek_topology()
        if topo is not None and topo.mesh.size > 1:
            return "einsum"
        return "sorted"

    @nn.compact
    def __call__(self, x: jax.Array, is_training: bool = True
                 ) -> Tuple[jax.Array, jax.Array]:
        cfg = self
        orig_shape = x.shape
        M, E, I = cfg.hidden_size, cfg.num_experts, cfg.intermediate_size
        x = x.reshape(-1, M)                                     # [G, M]

        # router in fp32 (reference TopKGate keeps the gate fp32,
        # sharded_moe.py:449) — routing decisions are precision-sensitive
        wg = self.param("gate", nn.initializers.lecun_normal(), (M, E),
                        jnp.float32)
        logits = x.astype(jnp.float32) @ wg                      # [G, E]

        noise_rng = None
        if (cfg.noisy_gate_policy == "Jitter" and is_training
                and self.has_rng("gating")):
            noise_rng = self.make_rng("gating")
        gr = topkgating(
            logits, k=cfg.k,
            capacity_factor=(cfg.capacity_factor if is_training
                             else cfg.eval_capacity_factor),
            min_capacity=cfg.min_capacity, drop_tokens=cfg.drop_tokens,
            noise_rng=noise_rng)

        ep = EXPERT_AXIS if cfg.expert_parallel else None
        tp = "tensor" if cfg.tensor_parallel else None

        def expert_param(name, shape, spec, bias: bool = False):
            init = (nn.initializers.zeros_init() if bias else
                    nn.initializers.lecun_normal(in_axis=-2, out_axis=-1,
                                                 batch_axis=(0,)))
            if any(s is not None for s in spec):
                init = nn.with_partitioning(init, spec)
            return self.param(name, init, shape, cfg.param_dtype)

        # dispatch: [G, M] -> [E, C, M]; the sharding constraint onto the
        # expert axis is the reference's first all-to-all (_AllToAll fwd)
        x_d = x.astype(cfg.dtype)      # one cast shared by all impls
        impl = cfg._resolve_dispatch()
        plan = None
        if impl == "gather":
            disp = moe_dispatch_gather(x_d, gr, cfg.num_experts)
        elif impl == "einsum":
            disp = moe_dispatch(x_d, gr.dispatch.astype(cfg.dtype))
        elif impl == "sorted":
            plan = routing_plan(gr, cfg.num_experts)
            disp = sorted_dispatch(x_d, plan.slot_token, plan.slot_of_copy)
        else:
            raise ValueError(f"unknown dispatch_impl {impl!r}")
        disp = _maybe_constrain(disp, (ep, None, None))

        if cfg.activation == "swiglu":                           # Mixtral
            w1 = expert_param("w1", (E, M, I), (ep, None, tp))
            w3 = expert_param("w3", (E, M, I), (ep, None, tp))
            w2 = expert_param("w2", (E, I, M), (ep, tp, None))
            h = jnp.einsum("ecm,emi->eci", disp, w1.astype(cfg.dtype))
            u = jnp.einsum("ecm,emi->eci", disp, w3.astype(cfg.dtype))
            out = jnp.einsum("eci,eim->ecm", nn.silu(h) * u,
                             w2.astype(cfg.dtype))
        elif cfg.activation == "gelu":
            w1 = expert_param("w1", (E, M, I), (ep, None, tp))
            b1 = expert_param("b1", (E, I), (ep, tp), bias=True)
            w2 = expert_param("w2", (E, I, M), (ep, tp, None))
            b2 = expert_param("b2", (E, M), (ep, None), bias=True)
            h = jnp.einsum("ecm,emi->eci", disp, w1.astype(cfg.dtype))
            h = jax.nn.gelu(h + b1.astype(cfg.dtype)[:, None])
            out = jnp.einsum("eci,eim->ecm", h, w2.astype(cfg.dtype))
            out = out + b2.astype(cfg.dtype)[:, None]
        else:
            raise ValueError(f"unknown MoE activation {cfg.activation!r}")

        out = _maybe_constrain(out, (ep, None, None))
        # combine: [E, C, M] -> [G, M] (the second all-to-all)
        if impl == "gather":
            y = moe_combine_gather(out, gr)
        elif impl == "sorted":
            y = sorted_combine(out, gr.weights, plan.slot_token,
                               plan.slot_of_copy)
        else:
            y = moe_combine(out, gr.combine.astype(cfg.dtype))
        return y.reshape(orig_shape), gr.l_aux.astype(jnp.float32)
