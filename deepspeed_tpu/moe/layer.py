"""Mixture-of-Experts layer.

TPU-native re-design of the reference MoE stack (``deepspeed/moe/layer.py:17
MoE``, ``sharded_moe.py:533 MOELayer``, ``TopKGate:449``): the reference
builds per-rank expert modules and issues explicit all-to-alls
(``_AllToAll:96``) between gate, experts, and combine; here the experts are
ONE stacked parameter tensor ``[E, ...]`` whose leading axis is annotated
onto the ``expert`` mesh axis.  Two multi-chip dispatch formulations exist:

- ``alltoall`` (the default on any multi-device mesh): the reference's own
  architecture — per-shard linear (sorted, gather-only) dispatch into
  ``[E, C_local, M]`` buffers, an explicit ``lax.all_to_all`` over the
  ``expert`` mesh axis (``_AllToAll:96``), local expert FFNs, and the
  inverse all-to-all — expressed as a ``jax.shard_map`` manual over the
  token-sharding axes while the ``tensor`` axis stays under automatic
  GSPMD (Megatron TP of the expert FFN still works).  Cost is LINEAR in
  tokens; capacity is per shard, matching the reference's per-rank counts.
- ``einsum``: dense one-hot dispatch/combine einsums sharding-constrained
  onto the expert axis so GSPMD inserts the all-to-alls.  Quadratic in
  token count (C ~ kG/E) — kept as the parity oracle and for meshes whose
  token sharding the alltoall path cannot express.

Expert-parallel composition mirrors ``groups.py:236
_create_expert_and_data_parallel``: the ``expert`` mesh axis carries both
the expert shards and (being a ZeRO axis) a slice of the data batch, so
ep_size experts x dp replicas works out of the box; MoE-aware ZeRO
(``stage_1_and_2.py:616 _configure_moe_settings``) falls out of the
sharding-plan composition — expert params keep their ``expert`` axis and
ZeRO claims a *different* dim.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map as _shard_map_compat

from deepspeed_tpu.moe.sharded_moe import (moe_combine, moe_combine_gather,
                                           moe_dispatch, moe_dispatch_gather,
                                           routing_plan, sorted_combine,
                                           sorted_dispatch, topkgating)
from deepspeed_tpu.utils.sharding import maybe_constrain as _maybe_constrain

EXPERT_AXIS = "expert"

# Engine-pinned topology for dispatch_impl='auto' (see
# MoE._resolve_dispatch): set by DeepSpeedEngine at build time so the
# resolution does not depend on WHEN flax traces the layer.
_AUTO_PIN_TOPO = None


def pin_auto_dispatch(topology) -> None:
    """Pin the topology that ``dispatch_impl='auto'`` resolves against
    when no live topology is installed at trace time.  The engine calls
    this at build; pass ``None`` to clear (tests)."""
    global _AUTO_PIN_TOPO
    _AUTO_PIN_TOPO = topology
# every mesh axis the flattened token dim may be sharded over (the engine's
# batch spec: data x data_sub x expert, plus seq under sequence parallelism)
TOKEN_AXES = ("data", "data_sub", "expert", "seq")


class MoE(nn.Module):
    """Top-k routed MoE FFN: gate -> dispatch -> experts -> combine.

    Returns ``(y, l_aux)``; the caller plumbs ``l_aux`` into the training
    loss (reference stores it on the layer and the engine collects it).
    """

    hidden_size: int
    num_experts: int
    intermediate_size: int
    k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    activation: str = "swiglu"             # "swiglu" (Mixtral) | "gelu"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    expert_parallel: bool = True           # annotate the expert mesh axis
    tensor_parallel: bool = False          # shard expert FFN over `tensor`
    noisy_gate_policy: Optional[str] = None  # None | "Jitter"
    # renormalize the selected experts' gates to sum to 1 (Mixtral); False
    # keeps raw softmax gates (Qwen2-MoE norm_topk_prob=False)
    normalize_weights: bool = True
    # "sorted": expert-sorted row gathers feeding the dense batched FFN —
    # linear in token count, no [G, E, C] one-hots, no scatter anywhere
    # (fwd or bwd); the TPU equivalent of the reference's grouped MoE
    # GEMM (cutlass_ops/moe_gemm).  Single-device only: the plan's global
    # argsort defeats GSPMD partitioning of sharded token axes.
    # "alltoall": the multi-chip linear path — per-shard sorted dispatch +
    # explicit lax.all_to_all over the expert axis under shard_map (the
    # reference MOELayer architecture, sharded_moe.py:533).
    # "einsum" is the reference's dense one-hot dispatch: G*E*C*M MACs
    # each way (QUADRATIC in G since C ~ kG/E) but expressed purely as
    # einsums, which GSPMD shards over any mesh — the parity oracle.
    # "gather" is the row-scatter path: measured ~20x slower on v5e (TPU
    # scatter lowering), CPU/debug only.
    # "auto" (default) resolves to "sorted" on single-device topologies
    # and "alltoall" on multi-device meshes (falling back to "einsum"
    # when the expert count does not divide over the expert axis).
    dispatch_impl: str = "auto"

    def _can_alltoall(self, topo, n_tokens: int) -> bool:
        ep = int(topo.mesh.shape.get(EXPERT_AXIS, 1))
        if self.num_experts % max(ep, 1) != 0:
            return False
        tok = 1
        for a in TOKEN_AXES:
            tok *= int(topo.mesh.shape.get(a, 1))
        # shard_map needs the flat token dim to divide over its axes
        # (tiny decode batches under a big training mesh fall back)
        return n_tokens % tok == 0

    def _resolve_dispatch(self, n_tokens: int) -> str:
        if self.dispatch_impl != "auto":
            return self.dispatch_impl
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.utils.logging import log_dist

        # engine-pinned topology first: DeepSpeedEngine resolves 'auto'
        # at BUILD time via pin_auto_dispatch, so a model traced before
        # the mesh installs (or after a transient mesh teardown) cannot
        # silently bake in the single-device choice.  A live topology
        # still wins — it is the mesh this trace actually runs under.
        topo = dist.peek_topology() or _AUTO_PIN_TOPO
        if topo is not None and topo.mesh.size > 1:
            impl = ("alltoall" if self._can_alltoall(topo, n_tokens)
                    else "einsum")
        else:
            impl = "sorted"
        log_dist(f"MoE dispatch_impl=auto -> {impl!r} "
                 f"(topology={'none' if topo is None else topo.mesh.shape})",
                 ranks=[0])
        return impl

    # -- expert FFN (shared by every dispatch impl) ----------------------

    def _expert_params(self):
        cfg = self
        M, E, I = cfg.hidden_size, cfg.num_experts, cfg.intermediate_size
        ep = EXPERT_AXIS if cfg.expert_parallel else None
        tp = "tensor" if cfg.tensor_parallel else None

        def expert_param(name, shape, spec, bias: bool = False):
            init = (nn.initializers.zeros_init() if bias else
                    nn.initializers.lecun_normal(in_axis=-2, out_axis=-1,
                                                 batch_axis=(0,)))
            if any(s is not None for s in spec):
                init = nn.with_partitioning(init, spec)
            return self.param(name, init, shape, cfg.param_dtype)

        if cfg.activation == "swiglu":                           # Mixtral
            return {"w1": expert_param("w1", (E, M, I), (ep, None, tp)),
                    "w3": expert_param("w3", (E, M, I), (ep, None, tp)),
                    "w2": expert_param("w2", (E, I, M), (ep, tp, None))}
        elif cfg.activation == "gelu":
            return {"w1": expert_param("w1", (E, M, I), (ep, None, tp)),
                    "b1": expert_param("b1", (E, I), (ep, tp), bias=True),
                    "w2": expert_param("w2", (E, I, M), (ep, tp, None)),
                    "b2": expert_param("b2", (E, M), (ep, None), bias=True)}
        raise ValueError(f"unknown MoE activation {cfg.activation!r}")

    def _expert_ffn(self, disp: jax.Array, w) -> jax.Array:
        """[E?, C, M] dispatched tokens -> [E?, C, M] expert outputs (the
        leading dim is global E on the einsum path, local E/ep under the
        alltoall shard_map)."""
        dt = self.dtype
        if self.activation == "swiglu":
            h = jnp.einsum("ecm,emi->eci", disp, w["w1"].astype(dt))
            u = jnp.einsum("ecm,emi->eci", disp, w["w3"].astype(dt))
            return jnp.einsum("eci,eim->ecm", nn.silu(h) * u,
                              w["w2"].astype(dt))
        h = jnp.einsum("ecm,emi->eci", disp, w["w1"].astype(dt))
        h = jax.nn.gelu(h + w["b1"].astype(dt)[:, None])
        out = jnp.einsum("eci,eim->ecm", h, w["w2"].astype(dt))
        return out + w["b2"].astype(dt)[:, None]

    # -- gating (shared) -------------------------------------------------

    def _gate(self, x: jax.Array, wg: jax.Array,
              noise_rng: Optional[jax.Array], is_training: bool):
        """Returns ``(GatingResult, fp32 logits)`` — the alltoall path
        needs the logits again for the global aux-loss pmean."""
        logits = x.astype(jnp.float32) @ wg                      # [G, E]
        return topkgating(
            logits, k=self.k,
            capacity_factor=(self.capacity_factor if is_training
                             else self.eval_capacity_factor),
            min_capacity=self.min_capacity, drop_tokens=self.drop_tokens,
            noise_rng=noise_rng,
            normalize_weights=self.normalize_weights), logits

    # -- the multi-chip linear path --------------------------------------

    def _alltoall_moe(self, x: jax.Array, wg: jax.Array, w,
                     noise_rng: Optional[jax.Array], is_training: bool
                     ) -> Tuple[jax.Array, jax.Array]:
        """Per-shard sorted dispatch + explicit all-to-all over ``expert``
        (reference ``_AllToAll:96`` + per-rank capacity, MOELayer:533).

        shard_map is manual over the token-sharding axes only; ``tensor``
        stays automatic so GSPMD still partitions the expert FFN einsums
        (Megatron TP) and inserts their psum.  Expert weights enter
        expert-sharded (any ZeRO sharding is gathered at the constraint
        below — the same per-layer gather ZeRO-3 implies)."""
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.sequence.layer import resolve_mesh

        cfg = self
        E = cfg.num_experts
        pinned = _AUTO_PIN_TOPO
        if dist.peek_topology() is None and pinned is not None:
            # traced without a live topology: the engine-pinned mesh is
            # the one this program will run under
            mesh = pinned.mesh
        else:
            mesh = resolve_mesh(None, EXPERT_AXIS)
        token_axes = tuple(a for a in TOKEN_AXES
                           if a in mesh.axis_names and
                           int(mesh.shape.get(a, 1)) > 1)
        # replicated experts (expert_parallel=False) need no all-to-all:
        # every shard holds all E experts and computes its own tokens
        ep = (int(mesh.shape.get(EXPERT_AXIS, 1)) if cfg.expert_parallel
              else 1)

        # gather any ZeRO shard dims; keep expert (+ tensor, automatic)
        ep_name = EXPERT_AXIS if cfg.expert_parallel else None
        tp = "tensor" if cfg.tensor_parallel else None
        w = dict(w)
        for k_, v in w.items():
            spec = [None] * v.ndim
            spec[0] = ep_name
            if tp is not None and v.ndim == 3:
                spec[2 if k_ in ("w1", "w3") else 1] = tp
            elif tp is not None and k_ == "b1":
                spec[1] = tp
            w[k_] = _maybe_constrain(v, tuple(spec))
        w_keys = sorted(w)
        w_vals = [w[k_] for k_ in w_keys]

        def wspec(v):
            s = [None] * v.ndim
            s[0] = ep_name
            return P(*s)

        if not token_axes:
            token_axes = None          # mesh.size>1 but batch unsharded
        has_rng = noise_rng is not None

        def body(x_l, wg_, *rest):
            rng = rest[0] if has_rng else None
            w_l = rest[1:] if has_rng else rest
            wd = dict(zip(w_keys, w_l))
            if rng is not None and token_axes:
                rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(token_axes))
            gr, logits = self._gate(x_l, wg_, rng, is_training)
            plan = routing_plan(gr, E)
            disp = sorted_dispatch(x_l.astype(cfg.dtype), plan.slot_token,
                                   plan.slot_of_copy)        # [E, C_l, M]
            if ep > 1:
                # reference _AllToAll fwd: expert-major buffers scatter to
                # their owning rank; each rank concatenates the C_l slices
                # it receives from every peer -> [E_l, ep*C_l, M]
                disp = jax.lax.all_to_all(disp, EXPERT_AXIS, split_axis=0,
                                          concat_axis=1, tiled=True)
            out = self._expert_ffn(disp, wd)
            if ep > 1:
                out = jax.lax.all_to_all(out, EXPERT_AXIS, split_axis=1,
                                         concat_axis=0, tiled=True)
            y = sorted_combine(out, gr.weights, plan.slot_token,
                               plan.slot_of_copy)
            l_aux = gr.l_aux
            if token_axes:
                # GLOBAL aux loss: average the per-expert token fraction
                # and router-prob fraction over every token shard BEFORE
                # the product, matching the global einsum formulation
                # bit-for-bit (mean of per-shard products differs —
                # product of means is nonlinear)
                me = jax.lax.pmean(
                    jnp.mean(jax.nn.softmax(logits, axis=-1), axis=0),
                    token_axes)
                ce = jax.lax.pmean(
                    jnp.mean(jax.nn.one_hot(gr.experts[0], E,
                                            dtype=jnp.float32), axis=0),
                    token_axes)
                l_aux = jnp.sum(me * ce) * E
            return y, l_aux

        manual = set(token_axes or ()) | {EXPERT_AXIS}
        tok_spec = P(token_axes, None)
        rng_args = (noise_rng,) if has_rng else ()
        rng_specs = (P(),) if has_rng else ()
        sm = _shard_map_compat(
            body, mesh=mesh,
            in_specs=(tok_spec, P()) + rng_specs +
                     tuple(wspec(v) for v in w_vals),
            out_specs=(tok_spec, P()),
            axis_names=manual, check_vma=False)
        # jit so eager callers (flax init, unit tests) route through the
        # jit lowering — jax's EAGER partial-manual shard_map impl trips
        # over meshes with extra (non-manual) axes; under an outer jit
        # this inlines
        return jax.jit(sm)(x, wg, *rng_args, *w_vals)

    # -- forward ---------------------------------------------------------

    @nn.compact
    def __call__(self, x: jax.Array, is_training: bool = True
                 ) -> Tuple[jax.Array, jax.Array]:
        cfg = self
        orig_shape = x.shape
        M, E = cfg.hidden_size, cfg.num_experts
        x = x.reshape(-1, M)                                     # [G, M]

        # router in fp32 (reference TopKGate keeps the gate fp32,
        # sharded_moe.py:449) — routing decisions are precision-sensitive
        wg = self.param("gate", nn.initializers.lecun_normal(), (M, E),
                        jnp.float32)
        w = self._expert_params()

        noise_rng = None
        if (cfg.noisy_gate_policy == "Jitter" and is_training
                and self.has_rng("gating")):
            noise_rng = self.make_rng("gating")

        impl = cfg._resolve_dispatch(x.shape[0])
        if impl == "alltoall":
            y, l_aux = self._alltoall_moe(x, wg, w, noise_rng, is_training)
            return y.reshape(orig_shape), l_aux.astype(jnp.float32)

        gr, _ = self._gate(x, wg, noise_rng, is_training)

        ep = EXPERT_AXIS if cfg.expert_parallel else None

        # dispatch: [G, M] -> [E, C, M]; the sharding constraint onto the
        # expert axis is the reference's first all-to-all (_AllToAll fwd)
        x_d = x.astype(cfg.dtype)      # one cast shared by all impls
        plan = None
        if impl == "gather":
            disp = moe_dispatch_gather(x_d, gr, cfg.num_experts)
        elif impl == "einsum":
            disp = moe_dispatch(x_d, gr.dispatch.astype(cfg.dtype))
        elif impl == "sorted":
            plan = routing_plan(gr, cfg.num_experts)
            disp = sorted_dispatch(x_d, plan.slot_token, plan.slot_of_copy)
        else:
            raise ValueError(f"unknown dispatch_impl {impl!r}")
        disp = _maybe_constrain(disp, (ep, None, None))

        out = self._expert_ffn(disp, w)

        out = _maybe_constrain(out, (ep, None, None))
        # combine: [E, C, M] -> [G, M] (the second all-to-all)
        if impl == "gather":
            y = moe_combine_gather(out, gr)
        elif impl == "sorted":
            y = sorted_combine(out, gr.weights, plan.slot_token,
                               plan.slot_of_copy)
        else:
            y = moe_combine(out, gr.combine.astype(cfg.dtype))
        return y.reshape(orig_shape), gr.l_aux.astype(jnp.float32)
