"""MoE gating and dispatch math.

TPU-native re-design of ``deepspeed/moe/sharded_moe.py`` (``top1gating:183``,
``top2gating:290``, ``topkgating:374``, ``MOELayer:533``, ``_capacity:161``).
Same einsum formulation — combine/dispatch tensors ``[tokens, experts,
capacity]`` with capacity-factor padding so shapes stay static under jit —
but the all-to-all dispatch is *implicit*: the dispatched tensor is
sharding-constrained onto the ``expert`` mesh axis and XLA/GSPMD emits the
all-to-all the reference issues by hand (``_AllToAll:96``), riding ICI.

Capacity here is computed from the GLOBAL token count (the reference uses
per-rank counts; global capacity is the natural formulation when dispatch is
a sharded einsum — same expected load, no per-rank imbalance artifacts).
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class GatingResult(NamedTuple):
    l_aux: jax.Array          # scalar load-balancing loss
    combine: jax.Array        # [G, E, C] float combine weights
    dispatch: jax.Array       # [G, E, C] bool dispatch mask
    exp_counts: jax.Array     # [E] tokens routed per expert (pre-drop)


def capacity(num_tokens: int, num_experts: int, capacity_factor: float,
             min_capacity: int, k: int = 1) -> int:
    """Static per-expert capacity (reference ``_capacity``,
    ``sharded_moe.py:161``; scaled by k so top-k routing has room)."""
    cap = int(np.ceil(k * capacity_factor * num_tokens / num_experts))
    return max(cap, min_capacity)


def topkgating(logits: jax.Array, k: int = 1,
               capacity_factor: float = 1.0, min_capacity: int = 4,
               drop_tokens: bool = True,
               noise_rng: Optional[jax.Array] = None,
               noise_eps: float = 1e-2) -> GatingResult:
    """Top-k gating with capacity-bounded dispatch.

    Covers the reference's ``top1gating``/``top2gating``/``topkgating``:
    iterative argmax selection, position-in-expert via token cumsum, gate
    normalization over the selected experts (top2-style), capacity drop, and
    the switch-transformer load-balancing aux loss from the first choice.
    """
    G, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    select_from = logits.astype(jnp.float32)
    if noise_rng is not None:  # multiplicative jitter (reference noisy_gate)
        select_from = select_from * jax.random.uniform(
            noise_rng, select_from.shape, minval=1.0 - noise_eps,
            maxval=1.0 + noise_eps)

    masks = []
    remaining = select_from
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        masks.append(mask)
        remaining = jnp.where(mask > 0, -jnp.inf, remaining)

    # aux loss: fraction of tokens * fraction of router prob per expert
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(masks[0], axis=0)
    l_aux = jnp.sum(me * ce) * E
    exp_counts = sum(jnp.sum(m, axis=0) for m in masks)

    if drop_tokens:
        C = capacity(G, E, capacity_factor, min_capacity, k=k)
    else:
        C = G  # worst case: every token to one expert

    # position of each token within its expert's capacity buffer: cumsum
    # over tokens, with later choices placed after all earlier choices
    positions, keeps = [], []
    offset = jnp.zeros((E,), jnp.float32)
    for mask in masks:
        loc = jnp.cumsum(mask, axis=0) - mask + offset[None, :]  # [G, E]
        offset = offset + jnp.sum(mask, axis=0)
        pos = jnp.sum(loc * mask, axis=-1).astype(jnp.int32)     # [G]
        positions.append(pos)
        keeps.append((pos < C).astype(jnp.float32))

    # gate values of the selected experts, normalized over the *surviving*
    # selection: the reference zeroes capacity-dropped choices in the masks
    # BEFORE computing gates1_s/gates2_s (top2gating, sharded_moe.py:290), so
    # when one choice drops the other absorbs the full weight (sums to 1)
    gate_k = [jnp.sum(gates * m, axis=-1) for m in masks]        # k x [G]
    denom = sum(g * keep for g, keep in zip(gate_k, keeps))
    denom = jnp.maximum(denom, jnp.finfo(jnp.float32).eps)

    combine = jnp.zeros((G, E, C), jnp.float32)
    for mask, g, pos, keep in zip(masks, gate_k, positions, keeps):
        w = g * keep / denom                                      # [G]
        combine = combine + (w[:, None, None] * mask[:, :, None] *
                             jax.nn.one_hot(pos, C, dtype=jnp.float32
                                            )[:, None, :])
    dispatch = combine > 0
    return GatingResult(l_aux=l_aux, combine=combine, dispatch=dispatch,
                        exp_counts=exp_counts)


def top1gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               **kw) -> GatingResult:
    return topkgating(logits, k=1, capacity_factor=capacity_factor,
                      min_capacity=min_capacity, **kw)


def top2gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               **kw) -> GatingResult:
    return topkgating(logits, k=2, capacity_factor=capacity_factor,
                      min_capacity=min_capacity, **kw)


def moe_dispatch(x: jax.Array, dispatch: jax.Array) -> jax.Array:
    """[G, M] tokens -> [E, C, M] expert buffers (reference
    ``einsum("sec,sm->ecm")``)."""
    return jnp.einsum("gec,gm->ecm", dispatch.astype(x.dtype), x)


def moe_combine(expert_out: jax.Array, combine: jax.Array) -> jax.Array:
    """[E, C, M] expert outputs -> [G, M] tokens (reference
    ``einsum("sec,ecm->sm")``)."""
    return jnp.einsum("gec,ecm->gm", combine.astype(expert_out.dtype),
                      expert_out)
