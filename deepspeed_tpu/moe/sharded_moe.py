"""MoE gating and dispatch math.

TPU-native re-design of ``deepspeed/moe/sharded_moe.py`` (``top1gating:183``,
``top2gating:290``, ``topkgating:374``, ``MOELayer:533``, ``_capacity:161``).
Same einsum formulation — combine/dispatch tensors ``[tokens, experts,
capacity]`` with capacity-factor padding so shapes stay static under jit —
but the all-to-all dispatch is *implicit*: the dispatched tensor is
sharding-constrained onto the ``expert`` mesh axis and XLA/GSPMD emits the
all-to-all the reference issues by hand (``_AllToAll:96``), riding ICI.

Capacity here is computed from the GLOBAL token count (the reference uses
per-rank counts; global capacity is the natural formulation when dispatch is
a sharded einsum — same expected load, no per-rank imbalance artifacts).
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class GatingResult(NamedTuple):
    l_aux: jax.Array          # scalar load-balancing loss
    combine: jax.Array        # [G, E, C] float combine weights
    dispatch: jax.Array       # [G, E, C] bool dispatch mask
    exp_counts: jax.Array     # [E] tokens routed per expert (pre-drop)
    # sparse routing view (the gather/scatter dispatch path; under jit the
    # dense combine/dispatch tensors are dead-code-eliminated when only
    # these are consumed): per choice k and token g —
    experts: jax.Array        # [k, G] int32 selected expert
    positions: jax.Array      # [k, G] int32 slot within the expert buffer
    weights: jax.Array        # [k, G] f32 renormalized combine weight
    #                           (0 for capacity-dropped choices)
    # (capacity C is static — recover it as combine.shape[-1])


def capacity(num_tokens: int, num_experts: int, capacity_factor: float,
             min_capacity: int, k: int = 1) -> int:
    """Static per-expert capacity (reference ``_capacity``,
    ``sharded_moe.py:161``; scaled by k so top-k routing has room)."""
    cap = int(np.ceil(k * capacity_factor * num_tokens / num_experts))
    return max(cap, min_capacity)


def topkgating(logits: jax.Array, k: int = 1,
               capacity_factor: float = 1.0, min_capacity: int = 4,
               drop_tokens: bool = True,
               noise_rng: Optional[jax.Array] = None,
               noise_eps: float = 1e-2,
               normalize_weights: bool = True) -> GatingResult:
    """Top-k gating with capacity-bounded dispatch.

    Covers the reference's ``top1gating``/``top2gating``/``topkgating``:
    iterative argmax selection, position-in-expert via token cumsum, gate
    normalization over the selected experts (top2-style), capacity drop, and
    the switch-transformer load-balancing aux loss from the first choice.

    ``normalize_weights=False`` keeps the raw softmax gate values of the
    selected experts (Qwen2-MoE ``norm_topk_prob=False``; the reference's
    topkgating exposes the same toggle, ``sharded_moe.py:374``).
    """
    G, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    select_from = logits.astype(jnp.float32)
    if noise_rng is not None:  # multiplicative jitter (reference noisy_gate)
        select_from = select_from * jax.random.uniform(
            noise_rng, select_from.shape, minval=1.0 - noise_eps,
            maxval=1.0 + noise_eps)

    masks, indices = [], []
    remaining = select_from
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        masks.append(mask)
        indices.append(idx.astype(jnp.int32))
        remaining = jnp.where(mask > 0, -jnp.inf, remaining)

    # aux loss: fraction of tokens * fraction of router prob per expert
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(masks[0], axis=0)
    l_aux = jnp.sum(me * ce) * E
    exp_counts = sum(jnp.sum(m, axis=0) for m in masks)

    if drop_tokens:
        C = capacity(G, E, capacity_factor, min_capacity, k=k)
    else:
        C = G  # worst case: every token to one expert

    # position of each token within its expert's capacity buffer: cumsum
    # over tokens, with later choices placed after all earlier choices
    positions, keeps = [], []
    offset = jnp.zeros((E,), jnp.float32)
    for mask in masks:
        loc = jnp.cumsum(mask, axis=0) - mask + offset[None, :]  # [G, E]
        offset = offset + jnp.sum(mask, axis=0)
        pos = jnp.sum(loc * mask, axis=-1).astype(jnp.int32)     # [G]
        positions.append(pos)
        keeps.append((pos < C).astype(jnp.float32))

    # gate values of the selected experts, normalized over the *surviving*
    # selection: the reference zeroes capacity-dropped choices in the masks
    # BEFORE computing gates1_s/gates2_s (top2gating, sharded_moe.py:290), so
    # when one choice drops the other absorbs the full weight (sums to 1)
    gate_k = [jnp.sum(gates * m, axis=-1) for m in masks]        # k x [G]
    if normalize_weights:
        denom = sum(g * keep for g, keep in zip(gate_k, keeps))
        denom = jnp.maximum(denom, jnp.finfo(jnp.float32).eps)
    else:
        denom = jnp.ones_like(gate_k[0])

    combine = jnp.zeros((G, E, C), jnp.float32)
    weights_k = []
    for mask, g, pos, keep in zip(masks, gate_k, positions, keeps):
        w = g * keep / denom                                      # [G]
        weights_k.append(w)
        combine = combine + (w[:, None, None] * mask[:, :, None] *
                             jax.nn.one_hot(pos, C, dtype=jnp.float32
                                            )[:, None, :])
    dispatch = combine > 0
    return GatingResult(l_aux=l_aux, combine=combine, dispatch=dispatch,
                        exp_counts=exp_counts,
                        experts=jnp.stack(indices),
                        positions=jnp.stack(positions),
                        weights=jnp.stack(weights_k))


def top1gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               **kw) -> GatingResult:
    return topkgating(logits, k=1, capacity_factor=capacity_factor,
                      min_capacity=min_capacity, **kw)


def top2gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               **kw) -> GatingResult:
    return topkgating(logits, k=2, capacity_factor=capacity_factor,
                      min_capacity=min_capacity, **kw)


def moe_dispatch(x: jax.Array, dispatch: jax.Array) -> jax.Array:
    """[G, M] tokens -> [E, C, M] expert buffers (reference
    ``einsum("sec,sm->ecm")``)."""
    return jnp.einsum("gec,gm->ecm", dispatch.astype(x.dtype), x)


def moe_combine(expert_out: jax.Array, combine: jax.Array) -> jax.Array:
    """[E, C, M] expert outputs -> [G, M] tokens (reference
    ``einsum("sec,ecm->sm")``)."""
    return jnp.einsum("gec,ecm->gm", combine.astype(expert_out.dtype),
                      expert_out)


def _dest_slots(gr: GatingResult, num_experts: int, cap: int) -> jax.Array:
    """[k, G] flat destination slot per routed copy; capacity-dropped
    copies point one past the end (scatter mode='drop' discards them)."""
    dest = gr.experts * cap + gr.positions
    return jnp.where(gr.weights > 0, dest, num_experts * cap)


def moe_dispatch_gather(x: jax.Array, gr: GatingResult,
                        num_experts: int) -> jax.Array:
    """[G, M] tokens -> [E, C, M] expert buffers by row scatter.

    Same result as :func:`moe_dispatch` with ~1% of the FLOPs, but NOTE:
    measured on TPU v5e the scatter lowering is ~20x SLOWER than the
    dense einsum (the einsum rides the MXU; the row scatter does not) —
    this path is for CPU/debug and as a parity oracle.  (expert,
    position) pairs are unique across choices by construction (later
    choices are offset past all earlier choices' counts), so the scatter
    has no collisions."""
    k, G = gr.weights.shape
    E, M = num_experts, x.shape[-1]
    C = gr.combine.shape[-1]
    dest = _dest_slots(gr, E, C).reshape(-1)                # [k*G]
    xk = jnp.broadcast_to(x[None], (k, G, M)).reshape(k * G, M)
    buf = jnp.zeros((E * C, M), x.dtype)
    # no unique_indices promise: dropped copies all alias the same
    # out-of-bounds slot before mode="drop" discards them
    buf = buf.at[dest].set(xk, mode="drop")
    return buf.reshape(E, C, M)


def moe_combine_gather(expert_out: jax.Array, gr: GatingResult
                       ) -> jax.Array:
    """[E, C, M] expert outputs -> [G, M] by row gather + weighted sum
    over the k choices (inverse of :func:`moe_dispatch_gather`)."""
    E, C, M = expert_out.shape
    flat = expert_out.reshape(E * C, M)
    dest = _dest_slots(gr, E, C)                            # [k, G]
    rows = flat.at[dest].get(mode="fill", fill_value=0)     # [k, G, M]
    w = gr.weights.astype(expert_out.dtype)[:, :, None]
    return jnp.sum(w * rows, axis=0)


# ---------------------------------------------------------------------------
# Sorted (gather-only) dispatch — the megablocks idea, TPU-shaped
# ---------------------------------------------------------------------------
# The dense one-hot dispatch/combine einsums cost G*E*C*M MACs each, and with
# C = k*G/E that is QUADRATIC in the token count — at the bench shapes it ties
# the FFN itself once micro-batches grow, and the [G, E, C] one-hots become
# multi-hundred-MB temporaries.  The reference's answer is a grouped CUTLASS
# GEMM over expert-sorted rows (inference/v2/kernels/cutlass_ops/moe_gemm/
# moe_gemm.cu); the TPU-native answer below reproduces the same sorted-rows
# layout with a stable argsort + row GATHERS (cost linear in G) feeding the
# SAME dense batched [E, C, M] FFN einsums that already ride the MXU.
#
# TPU scatter lowering is catastrophic (measured 20x the einsum path), so no
# scatter appears anywhere — including the BACKWARD: both permutation ops are
# custom-VJP'd so their gradients are gathers too (the inverse permutation is
# known statically from the forward plan).
#
# Ordering parity: within an expert, stable argsort over copy ids (choice-
# major, then token) reproduces exactly the position ordering topkgating
# computes (per-choice offset + token cumsum), so capacity drops select the
# SAME copies as the einsum path and outputs match bit-for-bit (modulo bf16
# summation order in the FFN).


class RoutingPlan(NamedTuple):
    slot_token: jax.Array     # [E, C] int32: token id filling each slot
    #                           (G = sentinel "empty"; rows gathered as 0)
    slot_of_copy: jax.Array   # [k, G] int32: flat slot e*C + c per copy
    #                           (E*C = sentinel "dropped")


def routing_plan(gr: GatingResult, num_experts: int) -> RoutingPlan:
    """Integer-only routing plan; no scatter, all O(kG log kG) sort work."""
    k, G = gr.experts.shape
    E = num_experts
    C = gr.combine.shape[-1]     # static; shape access does not materialize
    ec = gr.experts.reshape(-1)                       # [kG] expert per copy
    sort_idx = jnp.argsort(ec, stable=True)           # sorted copy ids
    inv = jnp.argsort(sort_idx, stable=True)          # copy -> sorted pos
    gs = jnp.sum(jax.nn.one_hot(ec, E, dtype=jnp.int32), axis=0)
    off = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                           jnp.cumsum(gs)[:-1].astype(jnp.int32)])
    cpos = jnp.arange(C, dtype=jnp.int32)[None, :]    # [1, C]
    src_pos = off[:, None] + cpos                     # [E, C] sorted position
    valid = cpos < gs[:, None]
    tok = jnp.tile(jnp.arange(G, dtype=jnp.int32), (k,))
    tok_sorted = jnp.take(tok, sort_idx, axis=0)
    slot_token = jnp.where(
        valid,
        jnp.take(tok_sorted, jnp.clip(src_pos, 0, k * G - 1).reshape(-1),
                 axis=0).reshape(E, C),
        G)
    c_of_copy = inv - jnp.take(off, ec, axis=0)
    slot_of_copy = jnp.where(c_of_copy < C, ec * C + c_of_copy, E * C)
    return RoutingPlan(slot_token=slot_token,
                       slot_of_copy=slot_of_copy.reshape(k, G))


def _take_rows(src: jax.Array, idx: jax.Array) -> jax.Array:
    return jnp.take(src, idx, axis=0)


def _pad_rows(x: jax.Array) -> jax.Array:
    """Append one zero row so sentinel indices gather zeros."""
    return jnp.concatenate([x, jnp.zeros((1,) + x.shape[1:], x.dtype)])


@jax.custom_vjp
def sorted_dispatch(x: jax.Array, slot_token: jax.Array,
                    slot_of_copy: jax.Array) -> jax.Array:
    """[G, M] tokens -> [E, C, M] expert buffers by row gather."""
    E, C = slot_token.shape
    return _take_rows(_pad_rows(x), slot_token.reshape(-1)).reshape(
        E, C, x.shape[-1])


def _sd_fwd(x, slot_token, slot_of_copy):
    return sorted_dispatch(x, slot_token, slot_of_copy), (slot_of_copy,)


def _sd_bwd(res, d):
    (slot_of_copy,) = res
    k = slot_of_copy.shape[0]
    E, C, M = d.shape
    dflat = _pad_rows(d.reshape(E * C, M))
    # d x[g] = sum over g's surviving copies of d disp[slot]; dropped copies
    # hit the zero sentinel row — a gather per choice, never a scatter
    dx = sum(_take_rows(dflat, slot_of_copy[j]) for j in range(k))
    return dx, None, None


sorted_dispatch.defvjp(_sd_fwd, _sd_bwd)


@jax.custom_vjp
def sorted_combine(expert_out: jax.Array, weights: jax.Array,
                   slot_token: jax.Array, slot_of_copy: jax.Array
                   ) -> jax.Array:
    """[E, C, M] expert outputs -> [G, M]: gather each copy's row, weighted
    sum over the k choices (weights are the gating's renormalized combine
    weights, 0 for capacity-dropped copies)."""
    E, C, M = expert_out.shape
    flat = _pad_rows(expert_out.reshape(E * C, M))
    rows = _take_rows(flat, slot_of_copy.reshape(-1)).reshape(
        slot_of_copy.shape + (M,))                    # [k, G, M]
    return jnp.sum(weights.astype(expert_out.dtype)[..., None] * rows,
                   axis=0)


def _sc_fwd(expert_out, weights, slot_token, slot_of_copy):
    return (sorted_combine(expert_out, weights, slot_token, slot_of_copy),
            (expert_out, weights, slot_token, slot_of_copy))


def _sc_bwd(res, dy):
    expert_out, weights, slot_token, slot_of_copy = res
    E, C, M = expert_out.shape
    k, G = weights.shape
    # d out[e,c] = w_of_slot * dy[token_of_slot]: both gathers.  The weight
    # of the copy occupying slot s is recovered per choice j by checking
    # whether token slot_token[s]'s j-th copy landed in s.
    flat_slots = jnp.arange(E * C, dtype=jnp.int32).reshape(E, C)
    d_rows = _take_rows(_pad_rows(dy), slot_token.reshape(-1)).reshape(
        E, C, M)
    w_slot = jnp.zeros((E, C), dy.dtype)
    for j in range(k):
        wj = _take_rows(
            jnp.concatenate([weights[j].astype(dy.dtype),
                             jnp.zeros((1,), dy.dtype)]),
            slot_token.reshape(-1)).reshape(E, C)
        copy_slot = _take_rows(
            jnp.concatenate([slot_of_copy[j],
                             jnp.full((1,), -1, jnp.int32)]),
            slot_token.reshape(-1)).reshape(E, C)
        w_slot = w_slot + jnp.where(copy_slot == flat_slots, wj, 0)
    dout = d_rows * w_slot[..., None]
    # d weights[j,g] = dy[g] . out_flat[slot_of_copy[j,g]]
    rows = _take_rows(_pad_rows(expert_out.reshape(E * C, M)),
                      slot_of_copy.reshape(-1)).reshape(k, G, M)
    dw = jnp.sum(rows.astype(jnp.float32) * dy.astype(jnp.float32)[None],
                 axis=-1)
    return dout, dw.astype(weights.dtype), None, None


sorted_combine.defvjp(_sc_fwd, _sc_bwd)
