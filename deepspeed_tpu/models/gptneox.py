"""GPT-NeoX model family (EleutherAI 20B / Pythia lineage).

Reference injects GPT-NeoX through its v1 policy
(``module_inject/containers/gptneox.py`` GPTNEOXLayerPolicy: fused
``query_key_value`` attention, Megatron-style TP split) — the last
member of the reference's gptj/gptneox parallel-residual class.  The
architecture: twin LayerNorms per block feeding attention and MLP
separately with ONE shared residual stream when
``use_parallel_residual`` (the 20B/Pythia default; sequential residuals
otherwise), partial HALF-LAYOUT rotary (``rotary_pct`` of each head —
natively our layout, no load-time permutation needed, unlike GPT-J's
interleaved checkpoints), biases everywhere, untied ``embed_out``.

Attention reuses :class:`deepspeed_tpu.models.llama.LlamaAttention`
(``attention_bias`` + ``attention_out_bias`` + ``partial_rotary_factor``
cover the NeoX shape), so GPT-NeoX trains and serves through every
Llama-family path: engine, v1 inference, AutoTP, ZeRO.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaAttention, LlamaConfig, _tp_kwargs


@dataclasses.dataclass(frozen=True)
class GPTNeoXConfig(LlamaConfig):
    layer_norm_eps: float = 1e-5
    rotary_pct: float = 0.25
    use_parallel_residual: bool = True


PRESETS = {
    "gpt-neox-20b": dict(vocab_size=50432, hidden_size=6144,
                         intermediate_size=24576, num_hidden_layers=44,
                         num_attention_heads=64, num_key_value_heads=64,
                         max_position_embeddings=2048, rotary_pct=0.25),
    "pythia-1.4b": dict(vocab_size=50304, hidden_size=2048,
                        intermediate_size=8192, num_hidden_layers=24,
                        num_attention_heads=16, num_key_value_heads=16,
                        max_position_embeddings=2048, rotary_pct=0.25),
    "pythia-6.9b": dict(vocab_size=50432, hidden_size=4096,
                        intermediate_size=16384, num_hidden_layers=32,
                        num_attention_heads=32, num_key_value_heads=32,
                        max_position_embeddings=2048, rotary_pct=0.25),
    "tinyneox": dict(vocab_size=96, hidden_size=32, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     rotary_pct=0.25),
}


def get_config(preset: str, **overrides) -> GPTNeoXConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    kw.setdefault("attention_bias", True)
    kw.setdefault("attention_out_bias", True)
    kw.setdefault("partial_rotary_factor", kw.get("rotary_pct", 0.25))
    return GPTNeoXConfig(**kw)


class GPTNeoXMLP(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        h = nn.Dense(cfg.intermediate_size, name="dense_h_to_4h", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        h = jax.nn.gelu(h.astype(jnp.float32), approximate=False).astype(
            cfg.dtype)
        return nn.Dense(cfg.hidden_size, name="dense_4h_to_h", **dense,
                        **_tp_kwargs(cfg, "row"))(h)


class GPTNeoXBlock(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        ln = dict(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                  param_dtype=jnp.float32)
        attn = LlamaAttention(cfg, name="attention")(
            nn.LayerNorm(name="input_layernorm", **ln)(x), positions,
            deterministic, ragged_meta)
        if cfg.use_parallel_residual:
            # x + attn(ln1(x)) + mlp(ln2(x)) — the 20B/Pythia layout
            mlp = GPTNeoXMLP(cfg, name="mlp")(
                nn.LayerNorm(name="post_attention_layernorm", **ln)(x))
            return x + attn + mlp
        h = x + attn
        return h + GPTNeoXMLP(cfg, name="mlp")(
            nn.LayerNorm(name="post_attention_layernorm", **ln)(h))


class ScanGPTNeoXBlock(nn.Module):
    config: GPTNeoXConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = GPTNeoXBlock(self.config, name="block")(x, positions,
                                                    self.deterministic)
        return (x, positions), None


class GPTNeoXModel(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed_in",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanGPTNeoXBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0
            (x, _), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="layers")((x, positions), None)
        else:
            block_cls = _maybe_remat(GPTNeoXBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, positions,
                                                       deterministic,
                                                       ragged_meta)
        return nn.LayerNorm(name="final_layer_norm",
                            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            param_dtype=jnp.float32)(x)


class GPTNeoXForCausalLM(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x = GPTNeoXModel(cfg, name="gpt_neox")(input_ids, positions,
                                               deterministic, ragged_meta)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="embed_out",
                        **_tp_kwargs(cfg, "col"))(x)


class GPTNeoXLMLoss(nn.Module):
    """``module(batch) -> scalar`` next-token CE (engine contract)."""

    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = GPTNeoXForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: GPTNeoXConfig,
                    seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    Dh, H = cfg.head_dim, cfg.num_attention_heads
    per_layer = 4 * E * H * Dh + 2 * E * I
    n = L * per_layer + 2 * cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
