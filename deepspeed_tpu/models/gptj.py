"""GPT-J model family (EleutherAI 6B lineage).

Reference injects GPT-J through its v1 policy + v2 container
(``module_inject/containers/gptj.py``, FastGen
``inference/v2/model_implementations``): parallel residual — one
``ln_1`` feeds both attention and MLP, whose outputs add into the
residual together — PARTIAL rotary embeddings (``rotary_dim`` of each
256-wide head, 64 for 6B), bias-free attention projections, a biased
GELU MLP (``fc_in``/``fc_out``), and a biased ``lm_head``.

Attention reuses :class:`deepspeed_tpu.models.llama.LlamaAttention`
(``partial_rotary_factor`` covers ``rotary_dim``), so GPT-J trains and
serves through every Llama-family path.  GPT-J checkpoints use the
INTERLEAVED (rotate-every-two) rotary layout; the HF loader permutes the
q/k projection rows of the rotary block into the half (NeoX) layout this
module computes — the attention scores are permutation-invariant, so
logits match exactly (``module_inject/hf_loader.py:_convert_gptj``).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaAttention, LlamaConfig, _tp_kwargs


@dataclasses.dataclass(frozen=True)
class GPTJConfig(LlamaConfig):
    layer_norm_epsilon: float = 1e-5
    rotary_dim: int = 64


PRESETS = {
    "gptj-6b": dict(vocab_size=50400, hidden_size=4096,
                    intermediate_size=16384, num_hidden_layers=28,
                    num_attention_heads=16, num_key_value_heads=16,
                    max_position_embeddings=2048, rotary_dim=64),
    "tinygptj": dict(vocab_size=96, hidden_size=32, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=64,
                     rotary_dim=4),
}


def get_config(preset: str, **overrides) -> GPTJConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    head_dim = kw["hidden_size"] // kw["num_attention_heads"]
    kw.setdefault("partial_rotary_factor", kw["rotary_dim"] / head_dim)
    return GPTJConfig(**kw)


class GPTJMLP(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        h = nn.Dense(cfg.intermediate_size, name="fc_in", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        h = jax.nn.gelu(h.astype(jnp.float32), approximate=True).astype(
            cfg.dtype)
        return nn.Dense(cfg.hidden_size, name="fc_out", **dense,
                        **_tp_kwargs(cfg, "row"))(h)


class GPTJBlock(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        h = nn.LayerNorm(name="ln_1", epsilon=cfg.layer_norm_epsilon,
                         dtype=cfg.dtype, param_dtype=jnp.float32)(x)
        attn = LlamaAttention(cfg, name="attn")(h, positions,
                                                deterministic, ragged_meta)
        # parallel residual: x + attn(ln(x)) + mlp(ln(x))
        return x + attn + GPTJMLP(cfg, name="mlp")(h)


class ScanGPTJBlock(nn.Module):
    config: GPTJConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = GPTJBlock(self.config, name="block")(x, positions,
                                                 self.deterministic)
        return (x, positions), None


class GPTJModel(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wte",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanGPTJBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0
            (x, _), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="h")((x, positions), None)
        else:
            block_cls = _maybe_remat(GPTJBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"h_{i}")(x, positions,
                                                  deterministic,
                                                  ragged_meta)
        return nn.LayerNorm(name="ln_f", epsilon=cfg.layer_norm_epsilon,
                            dtype=cfg.dtype, param_dtype=jnp.float32)(x)


class GPTJForCausalLM(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x = GPTJModel(cfg, name="transformer")(input_ids, positions,
                                               deterministic, ragged_meta)
        return nn.Dense(cfg.vocab_size, use_bias=True, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head",
                        **_tp_kwargs(cfg, "col"))(x)


class GPTJLMLoss(nn.Module):
    """``module(batch) -> scalar`` next-token CE (engine contract)."""

    config: GPTJConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = GPTJForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: GPTJConfig,
                    seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    Dh, H = cfg.head_dim, cfg.num_attention_heads
    per_layer = 4 * E * H * Dh + 2 * E * I
    n = L * per_layer + 2 * cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
