from deepspeed_tpu.models.gpt2 import (
    GPT2Config,
    GPT2Model,
    GPT2LMLoss,
    get_config,
    count_params,
    flops_per_token,
    PRESETS,
)

__all__ = ["GPT2Config", "GPT2Model", "GPT2LMLoss", "get_config",
           "count_params", "flops_per_token", "PRESETS"]
