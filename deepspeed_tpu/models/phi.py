"""Phi model family (phi-1 / phi-1.5 / phi-2).

Reference serves Phi through FastGen v2
(``inference/v2/model_implementations/phi/containers.py``): parallel
attention + MLP sharing one input LayerNorm (Falcon-style residual),
separate q/k/v/dense projections ALL with biases, PARTIAL rotary
(``partial_rotary_factor`` of each head's dims, 0.4 for phi-2), a
gelu_new MLP with biases, final LayerNorm, and an LM head WITH bias.

Attention reuses :class:`deepspeed_tpu.models.llama.LlamaAttention`
(the ``attention_bias`` / ``attention_out_bias`` /
``partial_rotary_factor`` knobs), so Phi decodes through the ragged v2
engine like the Llama family.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaAttention, LlamaConfig, _tp_kwargs


@dataclasses.dataclass(frozen=True)
class PhiConfig(LlamaConfig):
    layer_norm_eps: float = 1e-5
    attention_bias: bool = True
    attention_out_bias: bool = True
    partial_rotary_factor: float = 0.4


PRESETS = {
    "phi-1.5": dict(vocab_size=51200, hidden_size=2048,
                    intermediate_size=8192, num_hidden_layers=24,
                    num_attention_heads=32, num_key_value_heads=32,
                    max_position_embeddings=2048, rope_theta=10000.0,
                    partial_rotary_factor=0.5),
    "phi-2": dict(vocab_size=51200, hidden_size=2560,
                  intermediate_size=10240, num_hidden_layers=32,
                  num_attention_heads=32, num_key_value_heads=32,
                  max_position_embeddings=2048, rope_theta=10000.0,
                  partial_rotary_factor=0.4),
    "tinyphi": dict(vocab_size=96, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=4, max_position_embeddings=64,
                    partial_rotary_factor=0.5),
}


def get_config(preset: str, **overrides) -> PhiConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    return PhiConfig(**kw)


class PhiMLP(nn.Module):
    config: PhiConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        h = nn.Dense(cfg.intermediate_size, name="fc1", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        h = jax.nn.gelu(h.astype(jnp.float32), approximate=True).astype(
            cfg.dtype)
        return nn.Dense(cfg.hidden_size, name="fc2", **dense,
                        **_tp_kwargs(cfg, "row"))(h)


class PhiBlock(nn.Module):
    config: PhiConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        h = nn.LayerNorm(name="input_layernorm",
                         epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32)(x)
        attn = LlamaAttention(cfg, name="self_attn")(h, positions,
                                                     deterministic,
                                                     ragged_meta)
        # parallel residual: x + attn(ln(x)) + mlp(ln(x))
        return x + attn + PhiMLP(cfg, name="mlp")(h)


class ScanPhiBlock(nn.Module):
    config: PhiConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = PhiBlock(self.config, name="block")(x, positions,
                                                self.deterministic)
        return (x, positions), None


class PhiModel(nn.Module):
    config: PhiConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed_tokens",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanPhiBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0
            (x, _), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="layers")((x, positions), None)
        else:
            block_cls = _maybe_remat(PhiBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, positions,
                                                       deterministic,
                                                       ragged_meta)
        return nn.LayerNorm(name="final_layernorm",
                            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            param_dtype=jnp.float32)(x)


class PhiForCausalLM(nn.Module):
    config: PhiConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x = PhiModel(cfg, name="model")(input_ids, positions,
                                        deterministic, ragged_meta)
        return nn.Dense(cfg.vocab_size, use_bias=True, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head",
                        **_tp_kwargs(cfg, "col"))(x)


class PhiLMLoss(nn.Module):
    """``module(batch) -> scalar`` next-token CE (engine contract)."""

    config: PhiConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = PhiForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: PhiConfig, seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    Dh, H = cfg.head_dim, cfg.num_attention_heads
    per_layer = 4 * E * H * Dh + 2 * E * I
    n = L * per_layer + cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
