"""Falcon model family (7B lineage: parallel attention + MLP, MQA).

Reference serves Falcon through FastGen v2
(``inference/v2/model_implementations/falcon/container.py``): fused
``query_key_value`` (q heads, then k, then v — split on load like the
reference's FusedQKVParameter), rotary embeddings, multi-query attention
(``num_kv_heads=1``; the 40B+ lineage's GQA is the same knob), a GELU
MLP, and the 7B architecture's PARALLEL residual: one input LayerNorm
feeds both attention and MLP, whose outputs add into the residual
together.

Attention reuses :class:`deepspeed_tpu.models.llama.LlamaAttention`
verbatim — rotary + GQA + the flash / cached / paged ragged decode paths
are architecture-independent — so Falcon decodes through the ragged v2
engine like the Llama family.  The loader handles the 7B contiguous qkv
layout and the ``new_decoder_architecture`` (40B+) per-kv-group
interleave, and rejects the falcon-rw lineage's per-head interleave
loudly.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaAttention, LlamaConfig, _tp_kwargs


@dataclasses.dataclass(frozen=True)
class FalconConfig(LlamaConfig):
    # falcon uses LayerNorm (with bias), GELU MLP at 4*hidden, and the
    # parallel-residual block; num_key_value_heads=1 is the 7B MQA
    layer_norm_epsilon: float = 1e-5
    parallel_attn: bool = True
    new_decoder_architecture: bool = False   # 40B+: separate mlp LN


PRESETS = {
    "falcon-7b": dict(vocab_size=65024, hidden_size=4544,
                      intermediate_size=4 * 4544, num_hidden_layers=32,
                      num_attention_heads=71, num_key_value_heads=1,
                      max_position_embeddings=2048, rope_theta=10000.0),
    "falcon-40b": dict(vocab_size=65024, hidden_size=8192,
                       intermediate_size=4 * 8192, num_hidden_layers=60,
                       num_attention_heads=128, num_key_value_heads=8,
                       max_position_embeddings=2048, rope_theta=10000.0,
                       new_decoder_architecture=True),
    "tinyfalcon": dict(vocab_size=96, hidden_size=32,
                       intermediate_size=128, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=1,
                       max_position_embeddings=64),
}


def get_config(preset: str, **overrides) -> FalconConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    return FalconConfig(**kw)


class FalconMLP(nn.Module):
    config: FalconConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = dict(use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        h = nn.Dense(cfg.intermediate_size, name="dense_h_to_4h", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        h = jax.nn.gelu(h.astype(jnp.float32), approximate=False).astype(
            cfg.dtype)
        return nn.Dense(cfg.hidden_size, name="dense_4h_to_h", **dense,
                        **_tp_kwargs(cfg, "row"))(h)


class FalconBlock(nn.Module):
    config: FalconConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        ln = dict(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                  param_dtype=jnp.float32)
        h_attn = nn.LayerNorm(name="input_layernorm", **ln)(x)
        if cfg.new_decoder_architecture:
            h_mlp = nn.LayerNorm(name="ln_mlp", **ln)(x)
        else:
            h_mlp = h_attn
        attn = LlamaAttention(cfg, name="self_attention")(
            h_attn, positions, deterministic, ragged_meta)
        if cfg.parallel_attn:
            # 7B parallel residual: x + attn(ln(x)) + mlp(ln(x))
            return x + attn + FalconMLP(cfg, name="mlp")(h_mlp)
        x = x + attn
        h = nn.LayerNorm(name="post_attention_layernorm", **ln)(x)
        return x + FalconMLP(cfg, name="mlp")(h)


class ScanFalconBlock(nn.Module):
    config: FalconConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = FalconBlock(self.config, name="block")(x, positions,
                                                   self.deterministic)
        return (x, positions), None


class FalconModel(nn.Module):
    config: FalconConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="word_embeddings",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanFalconBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0
            (x, _), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="h")((x, positions), None)
        else:
            block_cls = _maybe_remat(FalconBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"h_{i}")(x, positions,
                                                  deterministic,
                                                  ragged_meta)
        return nn.LayerNorm(name="ln_f", epsilon=cfg.layer_norm_epsilon,
                            dtype=cfg.dtype, param_dtype=jnp.float32)(x)


class FalconForCausalLM(nn.Module):
    config: FalconConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x = FalconModel(cfg, name="transformer")(input_ids, positions,
                                                 deterministic, ragged_meta)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head",
                        **_tp_kwargs(cfg, "col"))(x)


class FalconLMLoss(nn.Module):
    """``module(batch) -> scalar`` next-token CE (engine contract)."""

    config: FalconConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = FalconForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: FalconConfig,
                    seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    Dh, H, Hkv = cfg.head_dim, cfg.num_attention_heads, cfg.num_key_value_heads
    per_layer = (E * H * Dh + 2 * E * Hkv * Dh + H * Dh * E + 2 * E * I)
    n = L * per_layer + cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
