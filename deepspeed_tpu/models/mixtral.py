"""Mixtral model family (sparse-MoE Llama).

BASELINE.md target #5 (Mixtral 8x7B expert-parallel MoE + ZeRO-3).  Reuses
the Llama attention/norm/RoPE stack (models/llama.py) and swaps the dense
MLP for the routed :class:`deepspeed_tpu.moe.MoE` layer; per-layer aux
losses thread through the scan carry and the LM-loss wrapper folds them
into the objective with ``router_aux_loss_coef`` (the reference collects
``l_aux`` off each ``MoE`` layer instead — ``sharded_moe.py:533``,
engine-side aggregation).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (LlamaAttention, LlamaConfig, RMSNorm,
                                        _tp_kwargs)
from deepspeed_tpu.moe.layer import MoE


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    router_aux_loss_coef: float = 0.02
    capacity_factor: float = 1.25
    min_capacity: int = 4
    drop_tokens: bool = True
    expert_parallel: bool = True
    # "auto" resolves per-topology: sorted (grouped-GEMM-style gathers)
    # when experts are device-local, einsum (GSPMD all-to-all) on a >1-way
    # expert mesh axis — see moe/layer.py dispatch_impl
    dispatch_impl: str = "auto"


PRESETS = {
    "mixtral-8x7b": dict(hidden_size=4096, intermediate_size=14336,
                         num_hidden_layers=32, num_attention_heads=32,
                         num_key_value_heads=8, vocab_size=32000,
                         num_local_experts=8, num_experts_per_tok=2,
                         rope_theta=1e6, max_position_embeddings=32768),
    "tinymixtral": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=64,
                        num_local_experts=4, num_experts_per_tok=2),
}


def get_config(preset: str, **overrides) -> MixtralConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return MixtralConfig(**kw)


def _moe(cfg: MixtralConfig, name: str) -> MoE:
    return MoE(hidden_size=cfg.hidden_size,
               num_experts=cfg.num_local_experts,
               intermediate_size=cfg.intermediate_size,
               k=cfg.num_experts_per_tok,
               capacity_factor=cfg.capacity_factor,
               min_capacity=cfg.min_capacity,
               drop_tokens=cfg.drop_tokens,
               activation="swiglu",
               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
               expert_parallel=cfg.expert_parallel,
               tensor_parallel=cfg.tensor_parallel,
               dispatch_impl=cfg.dispatch_impl,
               name=name)


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(x)
        x = x + LlamaAttention(cfg, name="self_attn")(h, positions,
                                                      deterministic,
                                                      ragged_meta)
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    name="post_attention_layernorm")(x)
        # is_training stays the MoE default (train capacity factor):
        # `deterministic` is a traced value under nn.remat, so the static
        # capacity selection cannot branch on it — serving engines that
        # want the eval capacity set capacity_factor on the decode config
        y, l_aux = _moe(cfg, "block_sparse_moe")(h)
        return x + y, l_aux


class ScanMixtralBlock(nn.Module):
    config: MixtralConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions, aux = carry
        x, l_aux = MixtralBlock(self.config, name="block")(
            x, positions, self.deterministic)
        return (x, positions, aux + l_aux), None


class MixtralModel(nn.Module):
    """Returns (hidden_states, mean-per-layer aux loss)."""

    config: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed_tokens",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        aux0 = jnp.asarray(0.0, jnp.float32)

        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanMixtralBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0         # per-layer KV buffers, stacked
            (x, _, aux), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True, "gating": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="layers")((x, positions, aux0), None)
        else:
            aux = aux0
            for i in range(cfg.num_hidden_layers):
                x, l_aux = _maybe_remat(MixtralBlock, cfg)(
                    cfg, name=f"layers_{i}")(x, positions, deterministic,
                                             ragged_meta)
                aux = aux + l_aux
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(x)
        return x, aux / cfg.num_hidden_layers


class MixtralForCausalLM(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x, aux = MixtralModel(cfg, name="model")(input_ids, positions,
                                                 deterministic, ragged_meta)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=cfg.param_dtype, name="lm_head",
                          **_tp_kwargs(cfg, "col"))(x)
        return logits, aux


class MixtralLMLoss(nn.Module):
    """``module(batch) -> scalar``: next-token CE + router aux loss."""

    config: MixtralConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits, aux = MixtralForCausalLM(self.config, name="lm")(input_ids)
        return (next_token_loss(logits, input_ids) +
                self.config.router_aux_loss_coef * aux)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: MixtralConfig,
                    seq_len: Optional[int] = None) -> float:
    """Fwd+bwd FLOPs/token counting only ACTIVE params (top-k experts)."""
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    Dh, H, Hkv = cfg.head_dim, cfg.num_attention_heads, cfg.num_key_value_heads
    per_layer = (E * H * Dh + 2 * E * Hkv * Dh + H * Dh * E
                 + cfg.num_experts_per_tok * 3 * E * I)
    n = L * per_layer + cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
