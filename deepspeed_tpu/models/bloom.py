"""BLOOM model family (BigScience 560m…176B lineage).

Reference injects BLOOM through its v1 policy container
(``module_inject/containers/bloom.py``: fused per-head ``[q;k;v]``
``query_key_value``, ALiBi position bias, biased LayerNorms and
projections): no rotary/learned positions — attention scores carry the
ALiBi per-head linear distance bias — an embedding LayerNorm after
``word_embeddings``, a biased GELU(tanh) MLP at 4×hidden, and an
lm_head tied to the input embedding.

ALiBi's bias ``-slope_h · (q_pos - k_pos)`` is constant along each
softmax row in ``q_pos``, so it reduces to ``slope_h · k_pos`` — a
per-head bias over KEY slots only — which is what both the training
kernel path and the decode cache path add (``cached_attention`` k_bias).

Scope follows the reference v1 container: training + v1 KV-cache
serving; the ragged v2 paged path and sequence-parallel attention do not
support ALiBi yet and fail loudly.  The lm_head is stored as its own
(loader-copied) matrix rather than weight-tied — training fine-tunes
them independently (documented divergence; serving parity is exact).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaConfig, _tp_kwargs


@dataclasses.dataclass(frozen=True)
class BloomConfig(LlamaConfig):
    layer_norm_epsilon: float = 1e-5


PRESETS = {
    "bloom-560m": dict(vocab_size=250880, hidden_size=1024,
                       intermediate_size=4096, num_hidden_layers=24,
                       num_attention_heads=16, num_key_value_heads=16,
                       max_position_embeddings=2048),
    "bloom-7b1": dict(vocab_size=250880, hidden_size=4096,
                      intermediate_size=16384, num_hidden_layers=30,
                      num_attention_heads=32, num_key_value_heads=32,
                      max_position_embeddings=2048),
    "tinybloom": dict(vocab_size=96, hidden_size=32, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=64),
}


def get_config(preset: str, **overrides) -> BloomConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    return BloomConfig(**kw)


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (the train-time head schedule from the
    ALiBi paper, as used by BLOOM/HF)."""

    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return np.asarray(pow2(n_heads), np.float32)
    closest = 2 ** math.floor(math.log2(n_heads))
    slopes = pow2(closest) + pow2(2 * closest)[0::2][:n_heads - closest]
    return np.asarray(slopes, np.float32)


class BloomAttention(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        if ragged_meta is not None or cfg.paged_decode:
            raise NotImplementedError(
                "ALiBi attention is not wired into the paged ragged "
                "path yet — serve BLOOM through the v1 engine")
        if cfg.sequence_parallel != "none":
            raise NotImplementedError(
                "ALiBi does not compose with sequence parallelism yet")
        B, S, E = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        q = nn.Dense(H * Dh, name="q_proj", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        k = nn.Dense(H * Dh, name="k_proj", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        v = nn.Dense(H * Dh, name="v_proj", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        q = q.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        slopes = jnp.asarray(alibi_slopes(H))

        if cfg.decode:
            from deepspeed_tpu.inference.kv_cache import (cached_attention,
                                                          update_kv_cache)

            max_len = cfg.max_cache_len or cfg.max_position_embeddings
            ragged = cfg.ragged_decode
            wp = positions[:, 0] if ragged else None
            k_full, v_full, _ = update_kv_cache(self, k, v, max_len,
                                                write_positions=wp)
            if S == 1 or ragged:
                k_bias = slopes[:, None] * jnp.arange(
                    k_full.shape[0], dtype=jnp.float32)[None, :]
                y = cached_attention(q, k_full, v_full, positions,
                                     k_bias=k_bias)
                y = y.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
                return nn.Dense(E, name="dense", **dense,
                                **_tp_kwargs(cfg, "row"))(y)
            # full prefill: cache written above; attend within the chunk

        from deepspeed_tpu.ops.flash_attention import mha_reference

        pos = positions if positions is not None else jnp.arange(S)
        if pos.ndim == 1:
            pos = pos[None]
        qpos = pos.astype(jnp.float32)                     # [1 or B, S]
        # ALiBi ≡ slope · k_pos along each row (the -slope·q_pos shift
        # cancels in softmax); mask strictly-future keys
        bias = slopes[None, :, None, None] * qpos[:, None, None, :]
        causal = qpos[:, None, :, None] >= qpos[:, None, None, :]
        bias = jnp.where(causal, bias, -1e30)
        y = mha_reference(q, k, v, causal=False, bias=bias)
        y = y.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
        return nn.Dense(E, name="dense", **dense,
                        **_tp_kwargs(cfg, "row"))(y)


class BloomMLP(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        h = nn.Dense(cfg.intermediate_size, name="dense_h_to_4h", **dense,
                     **_tp_kwargs(cfg, "col"))(x)
        h = jax.nn.gelu(h.astype(jnp.float32), approximate=True).astype(
            cfg.dtype)
        return nn.Dense(cfg.hidden_size, name="dense_4h_to_h", **dense,
                        **_tp_kwargs(cfg, "row"))(h)


class BloomBlock(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        ln = dict(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                  param_dtype=jnp.float32)
        h = nn.LayerNorm(name="input_layernorm", **ln)(x)
        x = x + BloomAttention(cfg, name="self_attention")(
            h, positions, deterministic, ragged_meta)
        h = nn.LayerNorm(name="post_attention_layernorm", **ln)(x)
        return x + BloomMLP(cfg, name="mlp")(h)


class ScanBloomBlock(nn.Module):
    config: BloomConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = BloomBlock(self.config, name="block")(x, positions,
                                                  self.deterministic)
        return (x, positions), None


class BloomModel(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        ln = dict(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                  param_dtype=jnp.float32)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="word_embeddings",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        x = nn.LayerNorm(name="word_embeddings_layernorm", **ln)(x)
        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanBloomBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0
            (x, _), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="h")((x, positions), None)
        else:
            block_cls = _maybe_remat(BloomBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"h_{i}")(x, positions,
                                                  deterministic,
                                                  ragged_meta)
        return nn.LayerNorm(name="ln_f", **ln)(x)


class BloomForCausalLM(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x = BloomModel(cfg, name="transformer")(input_ids, positions,
                                                deterministic, ragged_meta)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head",
                        **_tp_kwargs(cfg, "col"))(x)


class BloomLMLoss(nn.Module):
    """``module(batch) -> scalar`` next-token CE (engine contract)."""

    config: BloomConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = BloomForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: BloomConfig,
                    seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    Dh, H = cfg.head_dim, cfg.num_attention_heads
    per_layer = 4 * E * H * Dh + 2 * E * I
    n = L * per_layer + 2 * cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
