"""Phi-3 model family.

Reference serves Phi-3 through its FastGen v2 registry
(``inference/v2/model_implementations/phi3/model.py``,
``containers.py``): architecturally a Llama — RMSNorm, RoPE, GQA, SwiGLU,
untied LM head — whose HF checkpoints FUSE the attention projections into
one ``qkv_proj`` and the MLP gate/up into one ``gate_up_proj`` (the
reference maps them with ``FusedQKVParameter`` / ``FusedGatedMLPParameter``).

Here the module IS :class:`deepspeed_tpu.models.llama.LlamaForCausalLM`
(split projections are the better TPU layout — XLA fuses the three
matmuls' reads anyway and AutoTP shards each on its own dim); family
identity lives in :class:`Phi3Config` so the HF loader
(``module_inject/hf_loader.py``) knows to split the fused tensors.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                        LlamaLMLoss, count_params,
                                        flops_per_token)

__all__ = ["Phi3Config", "Phi3ForCausalLM", "Phi3LMLoss", "get_config",
           "count_params", "flops_per_token"]


@dataclasses.dataclass(frozen=True)
class Phi3Config(LlamaConfig):
    """Llama-shaped; the dataclass name routes the HF converter to the
    fused-weight splitter (reference ``phi3/containers.py`` PARAM_MAPPING:
    ``self_attn.qkv_proj.weight``, ``mlp.gate_up_proj.weight``)."""


# Phi-3 HF configs (microsoft/Phi-3-*): head_dim 96/128, vocab 32064
PRESETS = {
    "phi3-mini": dict(vocab_size=32064, hidden_size=3072,
                      intermediate_size=8192, num_hidden_layers=32,
                      num_attention_heads=32, num_key_value_heads=32,
                      max_position_embeddings=4096, rms_norm_eps=1e-5,
                      rope_theta=10000.0),
    # NO phi3-small preset: microsoft/Phi-3-small is NOT Llama-shaped
    # (blocksparse attention, gegelu MLP, qkv biases, tiktoken vocab) —
    # serving its checkpoint through this module would produce silently
    # wrong logits; get_config rejects it loudly instead.
    "phi3-medium": dict(vocab_size=32064, hidden_size=5120,
                        intermediate_size=17920, num_hidden_layers=40,
                        num_attention_heads=40, num_key_value_heads=10,
                        max_position_embeddings=4096, rope_theta=10000.0),
    "tinyphi3": dict(vocab_size=96, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=64),
}


def get_config(preset: str, **overrides) -> Phi3Config:
    if preset == "phi3-small":
        raise ValueError(
            "Phi-3-small uses blocksparse attention, the gegelu MLP and "
            "qkv biases — it is not Llama-shaped and this module would "
            "compute wrong logits for its checkpoints; only phi3-mini / "
            "phi3-medium are supported")
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    return Phi3Config(**kw)


class Phi3ForCausalLM(LlamaForCausalLM):
    """Same module; the subclass keeps ``type(model)(cfg)`` reconstruction
    (inference engines) inside the Phi-3 family."""


class Phi3LMLoss(LlamaLMLoss):
    pass
