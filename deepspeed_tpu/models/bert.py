"""BERT model family (the encoder class of the reference injection zoo).

Reference injects BertLayer through its v1 policy
(``module_inject/containers/bert.py`` HFBertLayerPolicy: fused qkv,
post-LayerNorm transformer, triangular masking off) — the only ENCODER
member of the injection zoo, serving embedding/classification workloads
through ``init_inference``.  Architecture: learned absolute positions +
token-type embeddings with an embedding LayerNorm, post-LN blocks
(attention -> residual+LN -> GELU MLP -> residual+LN), bidirectional
attention under an optional padding mask, and the MLM head (transform
dense + LN, decoder tied to the word embeddings).

TPU-first choices mirror the decoder families: ``nn.scan`` over blocks,
bf16 MXU matmuls, Megatron TP via the shared name-rule kwargs
(query/key/value/intermediate column-parallel, attention-output/output
row-parallel).  Serving is v1 ``forward()`` (full-sequence logits /
hidden states) — encoders have no autoregressive decode path, matching
the reference (BERT never routes to FastGen).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False
    remat_policy: str = "none"
    use_flash_attention: bool = False
    tensor_parallel: bool = False
    # engine-compat knobs (encoders never decode; asserted off).
    # is_encoder is the POSITIVE marker init_inference dispatches on —
    # a decoder config merely lacking max_cache_len is a config bug,
    # not an encoder
    is_encoder: bool = True
    decode: bool = False
    sequence_parallel: str = "none"
    pipeline_stages: int = 1

    def __post_init__(self):
        assert not self.decode, "BERT is an encoder: no decode path"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


@dataclasses.dataclass(frozen=True)
class DistilBertConfig(BertConfig):
    """DistilBERT: BERT-shaped minus token types (reference
    ``module_inject/containers/distil_bert.py`` HFDistilBertLayerPolicy).
    Served by the SAME modules — the converter zeroes the (size-1)
    token-type table and maps ``distilbert.*``/``vocab_*`` names."""

    type_vocab_size: int = 1


PRESETS = {
    "bert-base-uncased": dict(),
    "bert-large-uncased": dict(hidden_size=1024, num_hidden_layers=24,
                               num_attention_heads=16,
                               intermediate_size=4096),
    "tinybert": dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=64),
    "distilbert-base": dict(num_hidden_layers=6, layer_norm_eps=1e-12),
    "tinydistil": dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=4, intermediate_size=64,
                       max_position_embeddings=64),
}

_DISTIL = ("distilbert-base", "tinydistil")


def get_config(preset: str, **overrides) -> BertConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    if preset in _DISTIL:
        return DistilBertConfig(**kw)
    return BertConfig(**kw)


def _tp(cfg, kind: str):
    from deepspeed_tpu.parallel.tensor_parallel import tp_dense_kwargs

    return tp_dense_kwargs(cfg.tensor_parallel, kind)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attn_bias):
        cfg = self.config
        B, S, E = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        q = nn.Dense(H * Dh, name="query", **dense, **_tp(cfg, "col"))(x)
        k = nn.Dense(H * Dh, name="key", **dense, **_tp(cfg, "col"))(x)
        v = nn.Dense(H * Dh, name="value", **dense, **_tp(cfg, "col"))(x)
        q = q.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        from deepspeed_tpu.ops.flash_attention import (flash_attention,
                                                       mha_reference)

        if cfg.use_flash_attention and attn_bias is None:
            y = flash_attention(q, k, v, causal=False)
        else:
            y = mha_reference(q, k, v, causal=False, bias=attn_bias)
        return y.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)


class BertBlock(nn.Module):
    """Post-LN block (HF BertLayer): LN wraps residual SUMS, not inputs."""

    config: BertConfig

    @nn.compact
    def __call__(self, x, attn_bias=None):
        cfg = self.config
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        ln = dict(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                  param_dtype=jnp.float32)
        h = BertSelfAttention(cfg, name="attention")(x, attn_bias)
        h = nn.Dense(cfg.hidden_size, name="attention_output", **dense,
                     **_tp(cfg, "row"))(h)
        x = nn.LayerNorm(name="attention_layernorm", **ln)(x + h)
        i = nn.Dense(cfg.intermediate_size, name="intermediate", **dense,
                     **_tp(cfg, "col"))(x)
        i = jax.nn.gelu(i.astype(jnp.float32), approximate=False).astype(
            cfg.dtype)
        i = nn.Dense(cfg.hidden_size, name="output", **dense,
                     **_tp(cfg, "row"))(i)
        return nn.LayerNorm(name="output_layernorm", **ln)(x + i)


class ScanBertBlock(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, carry, _):
        x, bias = carry
        x = BertBlock(self.config, name="block")(x, bias)
        return (x, bias), None


class BertModel(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        emb = dict(dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                     name="word_embeddings", **emb)(input_ids)
        x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                         name="position_embeddings", **emb)(positions)
        x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                         name="token_type_embeddings", **emb)(token_type_ids)
        x = nn.LayerNorm(name="embeddings_layernorm",
                         epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32)(x)
        # padding mask -> additive bias [B, 1, 1, S] (bidirectional
        # attention: every query sees every non-pad key)
        bias = None
        if attention_mask is not None:
            bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                             -1e30).astype(jnp.float32)
        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanBertBlock, cfg)
            (x, _), _ = nn.scan(
                block_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layer")((x, bias), None)
        else:
            block_cls = _maybe_remat(BertBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layer_{i}")(x, bias)
        return x


class BertForMaskedLM(nn.Module):
    """BERT + MLM head (HF ``BertForMaskedLM``): transform dense + LN,
    decoder tied to the word embeddings plus a free output bias."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x = BertModel(cfg, name="bert")(input_ids, attention_mask,
                                        token_type_ids, positions,
                                        deterministic)
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        x = nn.Dense(cfg.hidden_size, name="transform", **dense)(x)
        x = jax.nn.gelu(x.astype(jnp.float32), approximate=False).astype(
            cfg.dtype)
        x = nn.LayerNorm(name="transform_layernorm",
                         epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32)(x)
        # HF ties the decoder to word_embeddings; here the converter
        # copies the tied weights into an explicit Dense (a flax parent
        # cannot cleanly read a child's params mid-apply) — numerically
        # identical, costs one extra V x E tensor
        return nn.Dense(cfg.vocab_size, name="decoder", **dense)(x)


class BertMLMLoss(nn.Module):
    """``module(batch) -> scalar`` masked-LM CE (engine contract).

    ``batch``: ``{"input_ids", "labels"}`` — positions with label -100
    are ignored (HF convention); without "labels" every position is
    scored against ``input_ids`` (identity objective, smoke use)."""

    config: BertConfig

    @nn.compact
    def __call__(self, batch):
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        labels = (batch.get("labels", input_ids)
                  if isinstance(batch, dict) else input_ids)
        mask_arg = batch.get("attention_mask") if isinstance(batch, dict) \
            else None
        logits = BertForMaskedLM(self.config, name="mlm")(
            input_ids, attention_mask=mask_arg)
        logits = logits.astype(jnp.float32)
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(valid.sum(), 1)
        return (jnp.where(valid, nll, 0.0).sum() / denom).astype(jnp.float32)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: BertConfig, seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    per_layer = 4 * E * E + 2 * E * I
    n = L * per_layer + cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * E * s
    return 6.0 * n + attn
