"""Qwen2-MoE model family (Qwen1.5-MoE / Qwen2-57B-A14B).

Reference serves this family through FastGen v2
(``inference/v2/model_implementations/qwen_v2_moe/model.py``,
``container.py``): Qwen2 attention (qkv biases) + a sparse MoE FFN whose
top-k gates are NOT renormalized (HF ``norm_topk_prob=False``) plus a
dense SHARED expert blended by a per-token sigmoid gate:

    y = moe(h) + sigmoid(shared_gate(h)) * shared_mlp(h)

TPU-first composition: attention/norms reuse ``models/llama.py`` (the
``attention_bias`` knob), the routed FFN is the
:class:`deepspeed_tpu.moe.MoE` layer (expert axis sharding, linear
all-to-all dispatch multi-chip), and the shared expert is a plain SwiGLU
MLP that stays dense on every rank — exactly the reference's
``shared_moe_*`` containers.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (LlamaAttention, LlamaMLP, RMSNorm,
                                        _tp_kwargs)
from deepspeed_tpu.models.mixtral import MixtralConfig
from deepspeed_tpu.moe.layer import MoE


@dataclasses.dataclass(frozen=True)
class Qwen2MoeConfig(MixtralConfig):
    # experts use their own (small) intermediate size; the shared expert
    # its own (large) one — HF Qwen2MoeConfig moe_intermediate_size /
    # shared_expert_intermediate_size
    moe_intermediate_size: int = 0          # 0 -> intermediate_size
    shared_expert_intermediate_size: int = 0  # 0 -> no shared expert
    norm_topk_prob: bool = False
    attention_bias: bool = True             # qkv biases (Qwen2 lineage)

    @property
    def expert_intermediate(self) -> int:
        return self.moe_intermediate_size or self.intermediate_size


PRESETS = {
    # Qwen1.5-MoE-A2.7B
    "qwen1.5-moe-a2.7b": dict(
        vocab_size=151936, hidden_size=2048, intermediate_size=5632,
        moe_intermediate_size=1408, shared_expert_intermediate_size=5632,
        num_hidden_layers=24, num_attention_heads=16,
        num_key_value_heads=16, num_local_experts=60,
        num_experts_per_tok=4, rope_theta=1e6,
        max_position_embeddings=8192, rms_norm_eps=1e-6),
    # Qwen2-57B-A14B
    "qwen2-57b-a14b": dict(
        vocab_size=151936, hidden_size=3584, intermediate_size=18944,
        moe_intermediate_size=2560, shared_expert_intermediate_size=20480,
        num_hidden_layers=28, num_attention_heads=28,
        num_key_value_heads=4, num_local_experts=64,
        num_experts_per_tok=8, rope_theta=1e6,
        max_position_embeddings=32768, rms_norm_eps=1e-6),
    "tinyqwen2moe": dict(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, shared_expert_intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-6),
}


def get_config(preset: str, **overrides) -> Qwen2MoeConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return Qwen2MoeConfig(**kw)


class Qwen2MoeBlock(nn.Module):
    config: Qwen2MoeConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(x)
        x = x + LlamaAttention(cfg, name="self_attn")(h, positions,
                                                      deterministic,
                                                      ragged_meta)
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    name="post_attention_layernorm")(x)
        y, l_aux = MoE(hidden_size=cfg.hidden_size,
                       num_experts=cfg.num_local_experts,
                       intermediate_size=cfg.expert_intermediate,
                       k=cfg.num_experts_per_tok,
                       capacity_factor=cfg.capacity_factor,
                       min_capacity=cfg.min_capacity,
                       drop_tokens=cfg.drop_tokens,
                       activation="swiglu",
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       expert_parallel=cfg.expert_parallel,
                       tensor_parallel=cfg.tensor_parallel,
                       dispatch_impl=cfg.dispatch_impl,
                       normalize_weights=cfg.norm_topk_prob,
                       name="mlp")(h)
        if cfg.shared_expert_intermediate_size:
            shared_cfg = dataclasses.replace(
                cfg, intermediate_size=cfg.shared_expert_intermediate_size)
            shared = LlamaMLP(shared_cfg, name="shared_expert")(h)
            gate = nn.Dense(1, use_bias=False, dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            name="shared_expert_gate")(h)
            y = y + jax.nn.sigmoid(gate.astype(jnp.float32)).astype(
                cfg.dtype) * shared
        return x + y, l_aux


class ScanQwen2MoeBlock(nn.Module):
    config: Qwen2MoeConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions, aux = carry
        x, l_aux = Qwen2MoeBlock(self.config, name="block")(
            x, positions, self.deterministic)
        return (x, positions, aux + l_aux), None


class Qwen2MoeModel(nn.Module):
    """Returns (hidden_states, mean-per-layer aux loss)."""

    config: Qwen2MoeConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed_tokens",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        aux0 = jnp.asarray(0.0, jnp.float32)

        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanQwen2MoeBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0
            (x, _, aux), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True, "gating": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="layers")((x, positions, aux0), None)
        else:
            aux = aux0
            for i in range(cfg.num_hidden_layers):
                x, l_aux = _maybe_remat(Qwen2MoeBlock, cfg)(
                    cfg, name=f"layers_{i}")(x, positions, deterministic,
                                             ragged_meta)
                aux = aux + l_aux
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(x)
        return x, aux / cfg.num_hidden_layers


class Qwen2MoeForCausalLM(nn.Module):
    config: Qwen2MoeConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x, aux = Qwen2MoeModel(cfg, name="model")(input_ids, positions,
                                                  deterministic, ragged_meta)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=cfg.param_dtype, name="lm_head",
                          **_tp_kwargs(cfg, "col"))(x)
        return logits, aux


class Qwen2MoeLMLoss(nn.Module):
    """``module(batch) -> scalar``: next-token CE + router aux loss."""

    config: Qwen2MoeConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits, aux = Qwen2MoeForCausalLM(self.config, name="lm")(input_ids)
        return (next_token_loss(logits, input_ids) +
                self.config.router_aux_loss_coef * aux)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: Qwen2MoeConfig,
                    seq_len: Optional[int] = None) -> float:
    """Fwd+bwd FLOPs/token counting ACTIVE params (top-k experts + the
    always-on shared expert)."""
    E, L = cfg.hidden_size, cfg.num_hidden_layers
    Dh, H, Hkv = cfg.head_dim, cfg.num_attention_heads, cfg.num_key_value_heads
    per_layer = (E * H * Dh + 2 * E * Hkv * Dh + H * Dh * E
                 + cfg.num_experts_per_tok * 3 * E * cfg.expert_intermediate
                 + 3 * E * cfg.shared_expert_intermediate_size + E)
    n = L * per_layer + cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
