"""GPT-Neo model family (EleutherAI 125M–2.7B lineage).

Reference injects GPT-Neo through its v1 policy
(``module_inject/containers/gptneo.py`` HFGPTNEOLayerPolicy: separate
q/k/v linears, GPT-2-shaped block).  Architecture quirks this module
reproduces exactly: attention scores are NOT scaled by 1/sqrt(d) (the
models were trained that way), attention alternates GLOBAL and LOCAL
(256-token sliding window) layers, q/k/v projections carry no bias
while the out projection does, learned absolute positions, GELU(tanh)
MLP, tied LM head.

Layers alternate two attention types, so blocks are heterogeneous —
this family runs UNROLLED (``scan_layers`` is rejected; the 125M–2.7B
shapes unroll fine), pre-LN like GPT-2.  Serves through v1
``init_inference`` (KV-cache decode honors the local window via the
shared ``cached_attention`` window mask).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class GPTNeoConfig:
    vocab_size: int = 50257
    max_position_embeddings: int = 2048
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0             # 0 -> 4 * hidden
    window_size: int = 256
    # per-layer pattern, cycled over layers (HF attention_types)
    attention_layers: Tuple[str, ...] = ("global", "local")
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = False
    remat: bool = False
    remat_policy: str = "none"
    use_flash_attention: bool = False
    tensor_parallel: bool = False
    sequence_parallel: str = "none"
    pipeline_stages: int = 1
    decode: bool = False
    max_cache_len: int = 0

    def __post_init__(self):
        assert not self.scan_layers, (
            "GPT-Neo alternates global/local attention layers — blocks "
            "are heterogeneous, so scan-over-layers cannot apply; use "
            "scan_layers=False")
        # accept-and-ignore would silently change perf/memory behavior:
        # neither SP nor pipeline is wired for this family.  Flash IS
        # supported on the GLOBAL layers (the kernel takes sm_scale=1.0
        # for the family's unscaled scores); the 256-token LOCAL layers
        # keep the dense windowed mask — the kernel has no window
        # support
        assert self.sequence_parallel == "none", (
            "sequence parallelism is not wired for GPT-Neo")
        assert self.pipeline_stages <= 1, (
            "pipeline parallelism is not wired for GPT-Neo")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_dim(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    def layer_kind(self, i: int) -> str:
        return self.attention_layers[i % len(self.attention_layers)]


PRESETS = {
    "gpt-neo-125m": dict(hidden_size=768, num_hidden_layers=12,
                         num_attention_heads=12),
    "gpt-neo-1.3b": dict(hidden_size=2048, num_hidden_layers=24,
                         num_attention_heads=16),
    "gpt-neo-2.7b": dict(hidden_size=2560, num_hidden_layers=32,
                         num_attention_heads=20),
    "tinyneo": dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    window_size=8),
}


def get_config(preset: str, **overrides) -> GPTNeoConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    kw.setdefault("dtype", jnp.bfloat16)
    return GPTNeoConfig(**kw)


def _tp(cfg, kind: str):
    from deepspeed_tpu.parallel.tensor_parallel import tp_dense_kwargs

    return tp_dense_kwargs(cfg.tensor_parallel, kind)


class GPTNeoAttention(nn.Module):
    """Unscaled dot-product attention, global or 256-window local."""

    config: GPTNeoConfig
    kind: str = "global"

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, S, E = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        proj = dict(use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype)
        q = nn.Dense(E, name="q_proj", **proj, **_tp(cfg, "col"))(x)
        k = nn.Dense(E, name="k_proj", **proj, **_tp(cfg, "col"))(x)
        v = nn.Dense(E, name="v_proj", **proj, **_tp(cfg, "col"))(x)

        def heads(t):
            return t.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        window = cfg.window_size if self.kind == "local" else None
        out = dict(use_bias=True, dtype=cfg.dtype,
                   param_dtype=cfg.param_dtype)
        if cfg.decode:
            from deepspeed_tpu.inference.kv_cache import (cached_attention,
                                                          update_kv_cache)

            max_len = cfg.max_cache_len or cfg.max_position_embeddings
            k_full, v_full, start = update_kv_cache(self, k, v, max_len)
            if S == 1:
                y = cached_attention(q, k_full, v_full,
                                     (start + jnp.arange(S))[None],
                                     window=window, scale=1.0)
                y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
                return nn.Dense(E, name="out_proj", **out,
                                **_tp(cfg, "row"))(y)
            # prefill: cache written; attend within the chunk below
        # scores deliberately UNscaled (scale=1): GPT-Neo trains without
        # the 1/sqrt(d) factor, fp32 softmax.  Local layers whose window
        # does not bind (S <= window: windowed mask == plain causal) use
        # flash too — the llama.py sliding-window precedent
        if cfg.use_flash_attention and (window is None or S <= window):
            from deepspeed_tpu.ops.flash_attention import flash_attention

            y = flash_attention(q, k, v, causal=True, sm_scale=1.0)
        else:
            att = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
            pos = jnp.arange(S)
            keep = pos[None, :] <= pos[:, None]
            if window is not None:
                # local layers always take the dense windowed mask (the
                # flash kernel has no sliding-window support)
                keep &= pos[None, :] > pos[:, None] - window
            att = jnp.where(keep[None, None], att,
                            jnp.finfo(jnp.float32).min)
            att = jax.nn.softmax(att, axis=-1).astype(cfg.dtype)
            y = jnp.einsum("bhqk,bhkd->bhqd", att, v)
        y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
        return nn.Dense(E, name="out_proj", **out, **_tp(cfg, "row"))(y)


class GPTNeoMLP(nn.Module):
    config: GPTNeoConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        h = nn.Dense(cfg.ffn_dim, name="c_fc", **dense,
                     **_tp(cfg, "col"))(x)
        h = jax.nn.gelu(h.astype(jnp.float32), approximate=True).astype(
            cfg.dtype)
        return nn.Dense(cfg.hidden_size, name="c_proj", **dense,
                        **_tp(cfg, "row"))(h)


class GPTNeoBlock(nn.Module):
    config: GPTNeoConfig
    kind: str = "global"

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        ln = dict(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                  param_dtype=jnp.float32)
        x = x + GPTNeoAttention(cfg, self.kind, name="attn")(
            nn.LayerNorm(name="ln_1", **ln)(x), deterministic)
        return x + GPTNeoMLP(cfg, name="mlp")(
            nn.LayerNorm(name="ln_2", **ln)(x))


class GPTNeoModel(nn.Module):
    config: GPTNeoConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        emb = tp_embed_kwargs(cfg.tensor_parallel)
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wte", **emb)
        wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="wpe", **emb)
        if positions is None:
            positions = jnp.arange(S)[None, :]
        x = wte(input_ids) + wpe(positions)
        block_cls = _maybe_remat(GPTNeoBlock, cfg)
        for i in range(cfg.num_hidden_layers):
            x = block_cls(cfg, cfg.layer_kind(i), name=f"h_{i}")(
                x, deterministic)
        x = nn.LayerNorm(name="ln_f", epsilon=cfg.layer_norm_epsilon,
                         dtype=cfg.dtype, param_dtype=jnp.float32)(x)
        return wte.attend(x)                        # tied head


class GPTNeoForCausalLM(nn.Module):
    config: GPTNeoConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        return GPTNeoModel(self.config, name="transformer")(
            input_ids, positions, deterministic, ragged_meta)


class GPTNeoLMLoss(nn.Module):
    """``module(batch) -> scalar`` next-token CE (engine contract)."""

    config: GPTNeoConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = GPTNeoForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: GPTNeoConfig,
                    seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.ffn_dim, cfg.num_hidden_layers
    per_layer = 4 * E * E + 2 * E * I
    n = L * per_layer + cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * E * s
    return 6.0 * n + attn
