"""Qwen2 model family.

Llama-shaped (the reference serves it through
``inference/v2/model_implementations/qwen_v2``) with two deltas:
**biases on the q/k/v projections** (``attention_bias=True``; o_proj
stays bias-free) and tied word embeddings on the small checkpoints
(the HF converter falls back to ``embed_tokens`` for ``lm_head``
automatically).
"""
from __future__ import annotations

import dataclasses

from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                        LlamaModel, count_params,
                                        flops_per_token)

__all__ = ["Qwen2Config", "Qwen2Model", "Qwen2ForCausalLM",
           "get_config", "count_params", "flops_per_token"]


@dataclasses.dataclass(frozen=True)
class Qwen2Config(LlamaConfig):
    attention_bias: bool = True


PRESETS = {
    "qwen2-7b": dict(vocab_size=152064, hidden_size=3584,
                     intermediate_size=18944, num_hidden_layers=28,
                     num_attention_heads=28, num_key_value_heads=4,
                     rope_theta=1e6, max_position_embeddings=32768,
                     rms_norm_eps=1e-6),
    "qwen2-0.5b": dict(vocab_size=151936, hidden_size=896,
                       intermediate_size=4864, num_hidden_layers=24,
                       num_attention_heads=14, num_key_value_heads=2,
                       rope_theta=1e6, max_position_embeddings=32768,
                       rms_norm_eps=1e-6),
    "tinyqwen2": dict(vocab_size=256, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64),
}


def get_config(preset: str, **overrides) -> Qwen2Config:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return Qwen2Config(**kw)


class Qwen2Model(LlamaModel):
    config: Qwen2Config


class Qwen2ForCausalLM(LlamaForCausalLM):
    config: Qwen2Config
