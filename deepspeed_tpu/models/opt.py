"""OPT model family.

Reference serves OPT through FastGen v2
(``inference/v2/model_implementations/opt/container.py``): learned
positional embeddings with the family's +2 offset, separate q/k/v/out
projections WITH biases, pre-LayerNorm blocks, ReLU MLP, final LN, tied
LM head.  GPT-2-shaped rather than Llama-shaped (no rotary), so it
serves through the v1 engine's fused decode loop — the ragged paged path
requires per-token rotary positions.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    max_position_embeddings: int = 2048
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "full"
    use_flash_attention: bool = False
    tensor_parallel: bool = False
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0
    decode: bool = False
    max_cache_len: int = 0

    # OPT's HF implementation offsets positions by 2 (its pad/bos rows)
    POSITION_OFFSET = 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def n_positions(self) -> int:   # engine position-bound probes
        return self.max_position_embeddings


PRESETS = {
    "opt-125m": dict(hidden_size=768, ffn_dim=3072, num_hidden_layers=12,
                     num_attention_heads=12),
    "opt-1.3b": dict(hidden_size=2048, ffn_dim=8192, num_hidden_layers=24,
                     num_attention_heads=32),
    "opt-6.7b": dict(hidden_size=4096, ffn_dim=16384,
                     num_hidden_layers=32, num_attention_heads=32),
    "opt-13b": dict(hidden_size=5120, ffn_dim=20480, num_hidden_layers=40,
                    num_attention_heads=40),
    "tinyopt": dict(vocab_size=96, hidden_size=32, ffn_dim=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64),
}


def get_config(preset: str, **overrides) -> OPTConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return OPTConfig(**kw)


def _tp(cfg, kind):
    from deepspeed_tpu.parallel.tensor_parallel import tp_dense_kwargs

    return tp_dense_kwargs(cfg.tensor_parallel, kind, with_bias=True)


class OPTAttention(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, S, E = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        q = nn.Dense(E, name="q_proj", **dense, **_tp(cfg, "col"))(x)
        k = nn.Dense(E, name="k_proj", **dense, **_tp(cfg, "col"))(x)
        v = nn.Dense(E, name="v_proj", **dense, **_tp(cfg, "col"))(x)

        def heads(t):
            return t.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.decode:
            from deepspeed_tpu.inference.kv_cache import (cached_attention,
                                                          update_kv_cache)

            max_len = cfg.max_cache_len or cfg.max_position_embeddings
            k_full, v_full, start = update_kv_cache(self, k, v, max_len)
            if S == 1:
                y = cached_attention(q, k_full, v_full,
                                     (start + jnp.arange(S))[None])
                y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
                return nn.Dense(E, name="out_proj", **dense,
                                **_tp(cfg, "row"))(y)
        if cfg.use_flash_attention:
            from deepspeed_tpu.ops.flash_attention import flash_attention

            y = flash_attention(q, k, v, causal=True)
        else:
            scale = 1.0 / np.sqrt(Dh)
            att = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
            mask = jnp.tril(jnp.ones((S, S), dtype=bool))
            att = jnp.where(mask[None, None], att,
                            jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32),
                                 axis=-1).astype(cfg.dtype)
            y = jnp.einsum("bhqk,bhkd->bhqd", att, v)
        y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
        return nn.Dense(E, name="out_proj", **dense, **_tp(cfg, "row"))(y)


class OPTBlock(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        ln = dict(epsilon=1e-5, dtype=cfg.dtype, param_dtype=jnp.float32)
        h = nn.LayerNorm(name="self_attn_layer_norm", **ln)(x)
        x = x + OPTAttention(cfg, name="self_attn")(h, deterministic)
        h = nn.LayerNorm(name="final_layer_norm", **ln)(x)
        dense = dict(use_bias=True, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        h = nn.Dense(cfg.ffn_dim, name="fc1", **dense,
                     **_tp(cfg, "col"))(h)
        h = jax.nn.relu(h)
        h = nn.Dense(cfg.hidden_size, name="fc2", **dense,
                     **_tp(cfg, "row"))(h)
        return x + h


class ScanOPTBlock(nn.Module):
    config: OPTConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, _):
        return OPTBlock(self.config, name="block")(x,
                                                   self.deterministic), None


class OPTModel(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, input_ids, positions=None,
                 deterministic: bool = True):
        from deepspeed_tpu.models.gpt2 import _maybe_remat
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed_tokens",
                     **tp_embed_kwargs(cfg.tensor_parallel))(input_ids)
        # learned positions with OPT's historical +2 offset
        pos_tab = nn.Embed(
            cfg.max_position_embeddings + cfg.POSITION_OFFSET,
            cfg.hidden_size, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="embed_positions")
        x = x + pos_tab(jnp.atleast_1d(positions) + cfg.POSITION_OFFSET)

        if cfg.scan_layers:
            block_cls = _maybe_remat(ScanOPTBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0
            x, _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="layers")(x, None)
        else:
            block_cls = _maybe_remat(OPTBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, deterministic)
        return nn.LayerNorm(name="final_layer_norm", epsilon=1e-5,
                            dtype=cfg.dtype, param_dtype=jnp.float32)(x)


class OPTForCausalLM(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, input_ids, positions=None,
                 deterministic: bool = True):
        cfg = self.config
        x = OPTModel(cfg, name="model")(input_ids, positions,
                                        deterministic)
        from deepspeed_tpu.models.llama import _tp_kwargs

        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head",
                        **_tp_kwargs(cfg, "col"))(x)


class OPTLMLoss(nn.Module):
    """``module(batch) -> scalar`` next-token CE (engine contract)."""

    config: OPTConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = OPTForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: OPTConfig, seq_len: Optional[int] = None) -> float:
    E, I, L = cfg.hidden_size, cfg.ffn_dim, cfg.num_hidden_layers
    per_layer = 4 * E * E + 2 * E * I
    n = L * per_layer + cfg.vocab_size * E
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * E * s
    return 6.0 * n + attn
