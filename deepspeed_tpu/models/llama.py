"""Llama model family (flax, TPU-first).

The framework's flagship dense LLM (BASELINE.md north star: Llama-2-7B
ZeRO-3 at >=45% MFU on v5p-128).  Architecture follows the Llama-2/-3
lineage the reference serves through its inference model registry
(``deepspeed/inference/v2/model_implementations/llama_v2``) and its AutoTP
policies (``module_inject/auto_tp.py``): RMSNorm, rotary position
embeddings, grouped-query attention, SwiGLU MLP, untied LM head.

TPU-first choices mirror models/gpt2.py: ``nn.scan`` over blocks (O(1)
compile depth; one layer's params live at a time under ZeRO-3), ``nn.remat``
activation checkpointing, bf16 matmuls on the MXU, the Pallas flash
attention kernel, and Megatron TP via flax partitioning metadata
(q/k/v/gate/up column-parallel, o/down row-parallel — the same
classification the reference's AutoTP applies by name).
"""
from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_position_embeddings: int = 4096
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32          # < heads => GQA
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "full"             # see gpt2.remat_policy_fn
    use_flash_attention: bool = True
    tensor_parallel: bool = False
    # sequence parallelism: "none", "ulysses" (all-to-all), "ring" (ppermute)
    sequence_parallel: str = "none"
    pipeline_stages: int = 1               # see gpt2.GPT2Config
    pipeline_microbatches: int = 0
    # inference: thread a KV cache through attention (flax "cache"
    # collection); max_cache_len=0 -> max_position_embeddings
    decode: bool = False
    # ragged/continuous-batching decode (FastGen v2): per-sequence [B, S]
    # positions drive cache write offsets; explicit opt-in — shared slots
    # at different lengths make position-derived writes load-bearing
    ragged_decode: bool = False
    max_cache_len: int = 0
    # paged/blocked KV (FastGen v2 blocked_allocator + ragged kernels):
    # the KV cache is [num_pages, page_size, 2*Hkv, Dh] pages addressed by
    # a per-sequence page table; attention is the vLLM-TPU ragged paged
    # kernel over ONE fused token batch mixing decode tokens and prefill
    # chunks.  Requires scan_layers=False (the fused step threads dynamic
    # metadata the scan carry cannot) and a `ragged_meta` call kwarg.
    paged_decode: bool = False
    kv_page_size: int = 64
    kv_num_pages: int = 0                  # 0 -> engine must set it
    # paged KV pool storage format: "none" (model dtype), "fp8" (e4m3) or
    # "int8" — per-(row, head) scales, dequantized transiently at
    # attention (reference fp_quantizer KV configs)
    kv_cache_dtype: str = "none"
    # family knobs shared with Mistral/Qwen2 (both are Llama-shaped):
    # qkv-projection biases (Qwen2) and sliding-window attention
    # (Mistral) — None disables the window
    attention_bias: bool = False
    sliding_window: Optional[int] = None
    # Phi family: bias on the attention out-projection, and rotary over
    # only the first partial_rotary_factor * head_dim dims (phi-2: 0.4)
    attention_out_bias: bool = False
    partial_rotary_factor: float = 1.0
    # serving: "w8a8" makes every Dense consume per-channel int8 kernels
    # natively (dynamic activation quant + int8 MXU dot) — set by the
    # inference engines when quantize_weights engages, never for training
    weight_quant: str = "none"

    def __post_init__(self):
        assert self.sequence_parallel in ("none", "ulysses", "ring"), (
            f"sequence_parallel={self.sequence_parallel!r}: expected 'none', "
            "'ulysses' or 'ring'")
        if self.decode:
            assert self.sequence_parallel == "none", (
                "decode mode does not compose with sequence parallelism")
            assert self.pipeline_stages <= 1, (
                "decode mode does not compose with pipeline parallelism")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "llama2-7b": dict(hidden_size=4096, intermediate_size=11008,
                      num_hidden_layers=32, num_attention_heads=32,
                      num_key_value_heads=32),
    "llama2-13b": dict(hidden_size=5120, intermediate_size=13824,
                       num_hidden_layers=40, num_attention_heads=40,
                       num_key_value_heads=40),
    "llama2-70b": dict(hidden_size=8192, intermediate_size=28672,
                       num_hidden_layers=80, num_attention_heads=64,
                       num_key_value_heads=8),
    "llama3-8b": dict(vocab_size=128256, hidden_size=4096,
                      intermediate_size=14336, num_hidden_layers=32,
                      num_attention_heads=32, num_key_value_heads=8,
                      rope_theta=500000.0, max_position_embeddings=8192),
    # TinyLlama-1.1B shape: the single-chip stand-in for the 7B bench
    "llama-1b": dict(hidden_size=2048, intermediate_size=5632,
                     num_hidden_layers=22, num_attention_heads=32,
                     num_key_value_heads=4),
    "tinyllama": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64),
}


def get_config(preset: str, **overrides) -> LlamaConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return LlamaConfig(**kw)


def _tp_kwargs(cfg: LlamaConfig, kind: str):
    from deepspeed_tpu.parallel.tensor_parallel import tp_dense_kwargs

    return tp_dense_kwargs(cfg.tensor_parallel, kind)


def _wq_kwargs(cfg: LlamaConfig):
    from deepspeed_tpu.inference.quantization import \
        weight_quant_dense_kwargs

    return weight_quant_dense_kwargs(getattr(cfg, "weight_quant", "none"))


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(),
                           (x.shape[-1],), jnp.float32)
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + self.eps)
        return (y * scale).astype(self.dtype)


def rotary_embedding(x: jax.Array, positions: jax.Array,
                     theta: float) -> jax.Array:
    """Apply RoPE.  x: [B, H, S, D] (D even); positions: [S] or [B, S]."""
    D = x.shape[-1]
    inv_freq = 1.0 / (theta ** (np.arange(0, D, 2, dtype=np.float32) / D))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [...,S,D/2]
    if angles.ndim == 2:             # [S, D/2] -> broadcast over B, H
        angles = angles[None, None]
    else:                            # [B, S, D/2] -> broadcast over H
        angles = angles[:, None]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        B, S, E = x.shape
        H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        dense = dict(use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, **_wq_kwargs(cfg))
        # Qwen2: biases on q/k/v only, never on o_proj
        qkv = dict(dense, use_bias=cfg.attention_bias)
        q = nn.Dense(H * Dh, name="q_proj", **qkv,
                     **_tp_kwargs(cfg, "col"))(x)
        k = nn.Dense(Hkv * Dh, name="k_proj", **qkv,
                     **_tp_kwargs(cfg, "col"))(x)
        v = nn.Dense(Hkv * Dh, name="v_proj", **qkv,
                     **_tp_kwargs(cfg, "col"))(x)

        q = q.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, Hkv, Dh).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, Hkv, Dh).transpose(0, 2, 1, 3)
        rot = int(Dh * cfg.partial_rotary_factor)
        if rot >= Dh:
            q = rotary_embedding(q, positions, cfg.rope_theta)
            k = rotary_embedding(k, positions, cfg.rope_theta)
        else:
            # partial rotary (Phi family): rope the first `rot` dims,
            # pass the rest through untouched
            q = jnp.concatenate(
                [rotary_embedding(q[..., :rot], positions, cfg.rope_theta),
                 q[..., rot:]], axis=-1)
            k = jnp.concatenate(
                [rotary_embedding(k[..., :rot], positions, cfg.rope_theta),
                 k[..., rot:]], axis=-1)

        if cfg.paged_decode:
            # blocked-KV continuous batching: one fused token batch over
            # the paged cache (reference ragged_ops kernels + blocked
            # allocator) — see inference/paged.py
            from deepspeed_tpu.inference.paged import paged_update_and_attend

            assert ragged_meta is not None, (
                "paged_decode models require the engine's ragged_meta")
            assert B == 1, "paged token batches are [1, T]"
            y = paged_update_and_attend(self, q, k, v, ragged_meta, cfg)
            y = y.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
            return nn.Dense(E, name="o_proj",
                            **dict(dense, use_bias=cfg.attention_out_bias),
                            **_tp_kwargs(cfg, "row"))(y)

        if cfg.decode:
            from deepspeed_tpu.inference.kv_cache import (cached_attention,
                                                          update_kv_cache)

            max_len = cfg.max_cache_len or cfg.max_position_embeddings
            # ragged path (FastGen v2 continuous batching, explicit
            # config opt-in): rows write at their own [B, S] position
            # offsets and every call — decode step or chunked-prefill
            # chunk — attends to the cache under the positions mask
            ragged = cfg.ragged_decode
            if ragged:
                assert (positions is not None and positions.ndim == 2 and
                        positions.shape[0] == B), (
                    "ragged_decode requires per-sequence [B, S] positions")
            wp = positions[:, 0] if ragged else None
            k_full, v_full, _ = update_kv_cache(self, k, v, max_len,
                                                write_positions=wp)
            if S == 1 or ragged:
                y = cached_attention(q, k_full, v_full, positions,
                                     window=cfg.sliding_window)
                y = y.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
                return nn.Dense(E, name="o_proj",
                                **dict(dense,
                                       use_bias=cfg.attention_out_bias),
                                **_tp_kwargs(cfg, "row"))(y)
            # full-prefill: cache written above; attend within the chunk

        window = cfg.sliding_window
        if window is not None and S > window and \
                cfg.sequence_parallel != "none":
            # the SP paths all-to-all/ring over the FULL sequence; the
            # local-window mask below would silently attend within shards
            raise NotImplementedError(
                "sliding-window attention does not compose with sequence "
                "parallelism yet — raise sliding_window above the "
                "sequence length or disable sequence_parallel")
        if window is not None and S > window:
            # Mistral sliding window binds: causal AND within-window mask
            # via the reference kernel (the flash kernel has no window
            # support; window-bound shapes are rare in training)
            from deepspeed_tpu.ops.flash_attention import mha_reference

            pos = jnp.arange(S)
            keep = (pos[None, :] <= pos[:, None]) & \
                   (pos[None, :] > pos[:, None] - window)
            bias = jnp.where(keep, 0.0, -1e30)[None, None]
            y = mha_reference(q, k, v, causal=False, bias=bias)
        elif cfg.sequence_parallel == "ulysses":
            from deepspeed_tpu.sequence import ulysses_attention

            y = ulysses_attention(q, k, v, causal=True)
        elif cfg.sequence_parallel == "ring":
            from deepspeed_tpu.sequence import ring_attention

            y = ring_attention(q, k, v, causal=True)
        elif cfg.use_flash_attention:
            from deepspeed_tpu.ops.flash_attention import flash_attention

            y = flash_attention(q, k, v, causal=True)
        else:
            from deepspeed_tpu.ops.flash_attention import mha_reference

            y = mha_reference(q, k, v, causal=True)
        y = y.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
        return nn.Dense(E, name="o_proj",
                        **dict(dense, use_bias=cfg.attention_out_bias),
                        **_tp_kwargs(cfg, "row"))(y)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = dict(use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, **_wq_kwargs(cfg))
        gate = nn.Dense(cfg.intermediate_size, name="gate_proj", **dense,
                        **_tp_kwargs(cfg, "col"))(x)
        up = nn.Dense(cfg.intermediate_size, name="up_proj", **dense,
                      **_tp_kwargs(cfg, "col"))(x)
        return nn.Dense(cfg.hidden_size, name="down_proj", **dense,
                        **_tp_kwargs(cfg, "row"))(nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(x)
        x = x + LlamaAttention(cfg, name="self_attn")(h, positions,
                                                      deterministic,
                                                      ragged_meta)
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    name="post_attention_layernorm")(x)
        return x + LlamaMLP(cfg, name="mlp")(h)


class PipeLlamaBlock(nn.Module):
    """GPipe block adapter: ``(x, positions) -> x``."""

    config: LlamaConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, positions):
        return LlamaBlock(self.config, name="block")(x, positions,
                                                     self.deterministic)


class ScanLlamaBlock(nn.Module):
    config: LlamaConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = LlamaBlock(self.config, name="block")(x, positions,
                                                  self.deterministic)
        return (x, positions), None


class LlamaModel(nn.Module):
    config: LlamaConfig
    # every matmul kernel in this module tree consumes w8a8
    # QuantizedWeight leaves natively (see _wq_kwargs) — serving engines
    # key the int8-MXU path off this class flag.  ClassVar keeps flax's
    # dataclass transform from turning it into a constructor field
    w8a8_native: ClassVar[bool] = True

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.arange(S)
        if cfg.paged_decode:
            assert not cfg.scan_layers and cfg.pipeline_stages == 1, (
                "paged_decode requires unrolled layers (the fused step "
                "threads dynamic ragged metadata the scan carry cannot)")
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        embed_kwargs = tp_embed_kwargs(cfg.tensor_parallel)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed_tokens",
                     **embed_kwargs)(input_ids)

        from deepspeed_tpu.models.gpt2 import _maybe_remat

        if cfg.pipeline_stages > 1:
            from deepspeed_tpu.parallel.pipeline import GPipe

            x = GPipe(
                PipeLlamaBlock, (cfg, deterministic),
                n_layer=cfg.num_hidden_layers,
                n_stages=cfg.pipeline_stages,
                n_micro=cfg.pipeline_microbatches or cfg.pipeline_stages,
                remat_policy=cfg.remat_policy if cfg.remat else "none",
                name="layers")(x, positions)
        elif cfg.scan_layers:
            block_cls = _maybe_remat(ScanLlamaBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0         # per-layer KV buffers, stacked
            (x, _), _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="layers")((x, positions), None)
        else:
            block_cls = _maybe_remat(LlamaBlock, cfg)
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, positions,
                                                       deterministic,
                                                       ragged_meta)
        return RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(x)


class LlamaForCausalLM(nn.Module):
    config: LlamaConfig
    # class flag, not a dataclass field (see LlamaModel)
    w8a8_native: ClassVar[bool] = True

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True,
                 ragged_meta=None):
        cfg = self.config
        x = LlamaModel(cfg, name="model")(input_ids, positions, deterministic,
                                          ragged_meta)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head",
                        **_tp_kwargs(cfg, "col"), **_wq_kwargs(cfg))(x)


class LlamaLMLoss(nn.Module):
    """Loss-returning wrapper matching the engine's flax-module contract:
    ``module(batch) -> scalar`` next-token cross entropy in fp32."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, batch):
        from deepspeed_tpu.models.gpt2 import next_token_loss

        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = LlamaForCausalLM(self.config, name="lm")(input_ids)
        return next_token_loss(logits, input_ids)


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: LlamaConfig, seq_len: Optional[int] = None) -> float:
    """Fwd+bwd FLOPs/token (PaLM convention), for MFU."""
    E, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    Dh, H, Hkv = cfg.head_dim, cfg.num_attention_heads, cfg.num_key_value_heads
    per_layer = (E * H * Dh + 2 * E * Hkv * Dh + H * Dh * E  # qkvo
                 + 3 * E * I)                                # gate/up/down
    n = L * per_layer + cfg.vocab_size * E                   # + lm head
    s = seq_len or cfg.max_position_embeddings
    attn = 12 * L * H * Dh * s
    return 6.0 * n + attn
