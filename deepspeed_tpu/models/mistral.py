"""Mistral model family.

Llama-shaped (same module graph the reference's
``inference/v2/model_implementations/mistral`` serves: RMSNorm, RoPE,
GQA, SwiGLU, untied head) plus **sliding-window attention** — keys more
than ``sliding_window - 1`` positions behind a query are masked.  The
window threads through every attention path: full prefill (reference
kernel mask when the window binds; the causal flash kernel when it
doesn't), v1 cached decode, and the ragged paged kernel (its native
``sliding_window`` argument).

HF checkpoint conversion reuses the Llama converter verbatim
(``module_inject/hf_loader.py`` — identical tensor names/layout).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                        LlamaModel, count_params,
                                        flops_per_token)

__all__ = ["MistralConfig", "MistralModel", "MistralForCausalLM",
           "get_config", "count_params", "flops_per_token"]


@dataclasses.dataclass(frozen=True)
class MistralConfig(LlamaConfig):
    sliding_window: Optional[int] = 4096


PRESETS = {
    "mistral-7b": dict(vocab_size=32000, hidden_size=4096,
                       intermediate_size=14336, num_hidden_layers=32,
                       num_attention_heads=32, num_key_value_heads=8,
                       rope_theta=10000.0, sliding_window=4096,
                       max_position_embeddings=32768),
    "tinymistral": dict(vocab_size=256, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        sliding_window=16, max_position_embeddings=64),
}


def get_config(preset: str, **overrides) -> MistralConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return MistralConfig(**kw)


class MistralModel(LlamaModel):
    config: MistralConfig


class MistralForCausalLM(LlamaForCausalLM):
    config: MistralConfig
