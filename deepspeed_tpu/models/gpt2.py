"""GPT-2 model family (flax).

The engine's flagship dense LM for the baseline configs (BASELINE.md:
GPT-2 125M ZeRO-0 smoke, GPT-2 1.3B ZeRO-2).  Built TPU-first:

- ``scan_layers=True`` stacks the transformer blocks with ``nn.scan`` so the
  compiled program is O(1) in depth and — under ZeRO-3 — XLA gathers one
  layer's params at a time, bounding live parameters the way the reference's
  prefetch coordinator does (``stage3_max_live_parameters``).
- ``remat=True`` wraps each block in ``nn.remat`` (activation checkpointing,
  the ``jax.checkpoint`` analogue of ``runtime/activation_checkpointing``).
- all matmuls run in ``param_dtype``-independent ``dtype`` (bf16 on TPU) and
  hit the MXU; attention uses a single fused softmax over [B, H, S, S] which
  XLA tiles, or the Pallas flash kernel when enabled.

The test fixtures (tests/unit/simple_model equivalent) use tiny instances of
this same model, mirroring the reference's SimpleModel philosophy.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    # selective activation checkpointing (runtime/activation_checkpointing
    # equivalent): "full" recomputes everything, "dots" saves matmul outputs
    # with no batch dims (XLA recomputes only cheap elementwise ops — the
    # reference's partitioned-activations sweet spot), "none" disables remat
    remat_policy: str = "full"
    use_flash_attention: bool = False
    tie_word_embeddings: bool = True
    tensor_parallel: bool = False  # Megatron-style TP param annotations
    # pipeline parallelism: >1 pipelines the blocks over the `pipe` mesh
    # axis (embedding/head replicate across stages — SURVEY §7 divergence)
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0  # 0 -> pipeline_stages
    # inference: thread a KV cache through attention (flax "cache"
    # collection); max_cache_len=0 -> n_positions
    decode: bool = False
    max_cache_len: int = 0

    def __post_init__(self):
        if self.decode:
            assert self.pipeline_stages <= 1, (
                "decode mode does not compose with pipeline parallelism")

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


# Preset sizes (reference baseline configs; param counts approximate)
PRESETS = {
    "gpt2-125m": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-350m": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-760m": dict(n_embd=1536, n_layer=24, n_head=16),
    "gpt2-1.3b": dict(n_embd=2048, n_layer=24, n_head=32),
    "gpt2-2.7b": dict(n_embd=2560, n_layer=32, n_head=32),
    "gpt2-6.7b": dict(n_embd=4096, n_layer=32, n_head=32),
}


def get_config(preset: str, **overrides) -> GPT2Config:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return GPT2Config(**kw)


def _tp_dense_kwargs(cfg, kind: str):
    """kernel/bias init kwargs for Megatron-style TP ('col'umn or 'row')."""
    from deepspeed_tpu.parallel.tensor_parallel import tp_dense_kwargs

    return tp_dense_kwargs(cfg.tensor_parallel, kind, with_bias=True)


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        B, S, E = x.shape
        qkv = nn.Dense(3 * E, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="c_attn", **_tp_dense_kwargs(cfg, "col"))(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, cfg.n_head, cfg.head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.decode:
            from deepspeed_tpu.inference.kv_cache import (cached_attention,
                                                          update_kv_cache)

            max_len = cfg.max_cache_len or cfg.n_positions
            k_full, v_full, start = update_kv_cache(self, k, v, max_len)
            if S == 1:                     # decode step: attend to the cache
                y = cached_attention(q, k_full, v_full,
                                     (start + jnp.arange(S))[None])
                y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
                return nn.Dense(E, dtype=cfg.dtype,
                                param_dtype=cfg.param_dtype, name="c_proj",
                                **_tp_dense_kwargs(cfg, "row"))(y)
            # prefill: cache written above; attend within the chunk below
        if cfg.use_flash_attention:
            assert cfg.dropout == 0.0 or deterministic, (
                "flash attention has no attention-probability dropout; set "
                "dropout=0 or use_flash_attention=False for training with "
                "dropout")
            from deepspeed_tpu.ops.flash_attention import flash_attention

            y = flash_attention(q, k, v, causal=True)
        else:
            scale = 1.0 / np.sqrt(cfg.head_dim)
            att = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
            mask = jnp.tril(jnp.ones((S, S), dtype=bool))
            att = jnp.where(mask[None, None], att, jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            att = nn.Dropout(cfg.dropout)(att, deterministic=deterministic)
            y = jnp.einsum("bhqk,bhkd->bhqd", att, v)
        y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
        y = nn.Dense(E, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="c_proj", **_tp_dense_kwargs(cfg, "row"))(y)
        return nn.Dropout(cfg.dropout)(y, deterministic=deterministic)


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_fc",
                     **_tp_dense_kwargs(cfg, "col"))(x)
        h = jax.nn.gelu(h)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_proj",
                     **_tp_dense_kwargs(cfg, "row"))(h)
        return nn.Dropout(cfg.dropout)(h, deterministic=deterministic)


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=1e-5, dtype=cfg.dtype, name="ln_1")(x), deterministic)
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=1e-5, dtype=cfg.dtype, name="ln_2")(x), deterministic)
        return x


def remat_policy_fn(name: str):
    """Map a policy name to a jax.checkpoint policy (None = save nothing)."""
    policies = {
        "full": None,
        # "dots" is the short form of dots_with_no_batch_dims_saveable;
        # "dots_saveable" (the reference config name) additionally saves
        # batch-dim dots
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "checkpoint_dots": jax.checkpoint_policies.checkpoint_dots,
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    }
    if name not in policies:
        raise ValueError(f"unknown remat policy {name!r}; "
                         f"one of {sorted(policies)} or 'none'")
    return policies[name]


def _maybe_remat(block_cls, cfg):
    if not cfg.remat or cfg.remat_policy == "none":
        return block_cls
    return nn.remat(block_cls, prevent_cse=False,
                    policy=remat_policy_fn(cfg.remat_policy))


class ScanBlock(nn.Module):
    """Block adapted to nn.scan carry signature."""

    config: GPT2Config
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, _):
        return Block(self.config, name="block")(x, self.deterministic), None


class PipeBlock(nn.Module):
    """GPipe block adapter: ``(x) -> x`` with deterministic baked in."""

    config: GPT2Config
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        return Block(self.config, name="block")(x, self.deterministic)


class GPT2Model(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 positions=None):
        cfg = self.config
        B, S = input_ids.shape
        from deepspeed_tpu.parallel.tensor_parallel import tp_embed_kwargs

        embed_kwargs = tp_embed_kwargs(cfg.tensor_parallel)
        wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wte",
                       **embed_kwargs)
        wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wpe",
                       **embed_kwargs)
        if positions is None:
            positions = jnp.arange(S)[None, :]
        x = wte(input_ids) + wpe(positions)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        if cfg.pipeline_stages > 1:
            from deepspeed_tpu.parallel.pipeline import GPipe

            x = GPipe(
                PipeBlock, (cfg, deterministic), n_layer=cfg.n_layer,
                n_stages=cfg.pipeline_stages,
                n_micro=cfg.pipeline_microbatches or cfg.pipeline_stages,
                remat_policy=cfg.remat_policy if cfg.remat else "none",
                name="h")(x)
        elif cfg.scan_layers:
            block_cls = _maybe_remat(ScanBlock, cfg)
            vaxes = {"params": 0}
            if cfg.decode:
                vaxes["cache"] = 0         # per-layer KV buffers, stacked
            x, _ = nn.scan(
                block_cls,
                variable_axes=vaxes,
                split_rngs={"params": True, "dropout": True},
                length=cfg.n_layer,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, deterministic, name="h")(x, None)
        else:
            block_cls = _maybe_remat(Block, cfg)
            for i in range(cfg.n_layer):
                x = block_cls(cfg, name=f"h_{i}")(x, deterministic)

        x = nn.LayerNorm(epsilon=1e-5, dtype=cfg.dtype, name="ln_f")(x)
        if cfg.tie_word_embeddings:
            logits = wte.attend(x)
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype, name="lm_head")(x)
        return logits


class GPT2LMLoss(nn.Module):
    """Loss-returning wrapper: ``module(batch) -> scalar`` as the engine's
    flax-module contract expects.  ``batch`` is ``{"input_ids": [B, S]}`` or
    a raw [B, S] array; next-token cross entropy in fp32."""

    config: GPT2Config

    @nn.compact
    def __call__(self, batch):
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        deterministic = self.config.dropout == 0.0
        logits = GPT2Model(self.config, name="transformer")(
            input_ids, deterministic=deterministic)
        return next_token_loss(logits, input_ids)


def next_token_loss(logits: jax.Array, input_ids: jax.Array) -> jax.Array:
    """Next-token cross entropy without materializing an fp32 [B, S, V]
    log-softmax: loss = mean(lse - target_logit).  The [B, S, V] tensor stays
    in the model compute dtype (bf16); only the logsumexp reduction and the
    gathered target logits are fp32 (XLA fuses the upcast into the reduce,
    so nothing V-sized is ever written in fp32).  Backward is the standard
    softmax-minus-onehot, likewise fused from the bf16 logits."""
    logits = logits[:, :-1]
    targets = input_ids[:, 1:]
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt.astype(jnp.float32))


def count_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: GPT2Config, seq_len: Optional[int] = None) -> float:
    """Model fwd+bwd FLOPs per token for MFU (PaLM-appendix convention):
    ``6 * N_matmul + 12 * L * E * S`` where ``N_matmul`` counts matmul
    params (block weights + the LM head; embedding lookups are gathers)."""
    n = (12 * cfg.n_layer * cfg.n_embd ** 2 +
         cfg.vocab_size * cfg.n_embd)
    s = seq_len or cfg.n_positions
    attn = 12 * cfg.n_layer * cfg.n_embd * s
    return 6.0 * n + attn
