"""Scale-out serving: replicated ragged engines behind an SLO-aware
router with continuous admission control.

Data parallelism across engine REPLICAS composes with the tensor
parallelism each engine already runs inside (GSPMD annotations over a
mesh slice): on TPU one replica owns one mesh slice; on the CPU tier-1
path replicas are thread-per-replica against the host platform
(``--xla_force_host_platform_device_count`` splits the host into
devices when real overlap is wanted — on a 1-core container the
threads interleave but results stay bit-identical).

- :class:`~.replica_set.ReplicaSet` / :class:`~.replica_set.EngineReplicaHandle`
  — N engines, each on its own single-worker thread, fed through a
  bounded window (a third instance of the
  :class:`~deepspeed_tpu.utils.async_stage.BoundedAsyncStage`
  substrate, after the engine's pipelined decode carry and the NVMe
  moment stream).
- :class:`~.router.Router` — the front end: pluggable load-balancing
  policies (``rr`` / ``least_tokens`` / ``pressure``), sticky routing
  for prefix-cache affinity (prompt-prefix chain hash), and an
  admission controller (priorities, per-replica queue caps, SLO
  burn-rate shed/defer, request deadlines) with loud typed rejections.
- Fault tolerance: a per-replica liveness watchdog
  (``ReplicaSet(watchdog_s=...)`` bounds every feed/step join;
  :class:`~.replica_set.ReplicaHangError` on a wedge) under a typed
  health breaker (:class:`~.router.BreakerConfig` —
  healthy/suspect/dead/probation, hedged re-dispatch of unadmitted
  requests off suspects, revival probes through the ReplicaSet
  factory, flap freeze).
- :class:`~.server.FrontDoorServer` — the network front door: a
  stdlib-asyncio HTTP/1.1 + SSE endpoint over the router with token
  streaming at harvest granularity, client-disconnect cancellation
  that reclaims pool pages mid-decode, deadline admission, and
  SIGTERM graceful drain with warm-state handoff.
- :mod:`~.client` — asyncio SSE client + open-loop Poisson /
  closed-loop load generator measuring TTFT/TPOT at the socket.
"""
from deepspeed_tpu.serving.replica_set import (EngineReplicaHandle,
                                               ReplicaHangError,
                                               ReplicaSet)
from deepspeed_tpu.serving.router import (BreakerConfig,
                                          DeadlineRejection,
                                          DrainingRejection,
                                          NeverSchedulableRejection,
                                          POLICIES, QueueFullRejection,
                                          REPLICA_STATES, Router,
                                          RouterRejection, ShedRejection)

__all__ = ["ReplicaSet", "EngineReplicaHandle", "Router", "POLICIES",
           "RouterRejection", "QueueFullRejection", "ShedRejection",
           "NeverSchedulableRejection", "DeadlineRejection",
           "DrainingRejection", "FrontDoorServer", "BreakerConfig",
           "ReplicaHangError", "REPLICA_STATES"]


def __getattr__(name):
    # server/client import asyncio machinery; keep the base package
    # import light by resolving them lazily
    if name == "FrontDoorServer":
        from deepspeed_tpu.serving.server import FrontDoorServer
        return FrontDoorServer
    raise AttributeError(name)
