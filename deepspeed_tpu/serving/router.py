"""SLO-aware router over a ReplicaSet: policies, sticky prefix
affinity, and continuous admission control.

The router is single-threaded by construction: all of its state
mutates on the caller's thread, either directly in ``submit``/``pump``
or inside ``on_done`` folds that the replica feed windows run at join
time (see :mod:`~.replica_set`).  Replica engines run on their own
threads; the router only ever talks to them through handle ops.

Admission is CONTINUOUS, not a one-shot gate: every ``submit`` sees the
current per-replica queue depths and the SLO burn rate, so a burst that
fills the queues starts shedding mid-burst and stops shedding as soon
as the replicas drain — the open-loop analogue of the engine's
submit-time ``put_request`` rejection.

Rejections are loud and typed (the ISSUE's "loud typed rejections"):

- :class:`NeverSchedulableRejection` — the request could never run on
  ANY replica (the engine's tier-aware schedulability check, surfaced
  at the front door instead of deep inside a replica queue).
- :class:`QueueFullRejection` — every live replica is at its
  queue-depth cap (default ``2 * max_seqs``, seeded from the engine's
  admission geometry).
- :class:`ShedRejection` — SLO error-budget burn rate crossed
  ``burn_shed`` and the request's priority is below the protected
  tier.  Between ``burn_defer`` and ``burn_shed`` low-priority
  requests are accepted but HELD in the router queue (deferred) while
  high-priority traffic keeps dispatching.
- :class:`DeadlineRejection` — the request carried ``deadline_ms`` and
  the deadline had already burned at submit.  An ACCEPTED request
  whose deadline burns while still queued expires lazily in the
  priority heap (never occupies a slot) and surfaces as a
  ``deadline_expired`` event.
- :class:`DrainingRejection` — the router is in graceful drain
  (``begin_drain``): in-flight work finishes, new work is refused
  (the front door maps this to 503 + Retry-After).

Streaming front ends set ``collect_events = True`` and drain
``poll_events()`` after each ``pump``/``join`` round: ``("tokens",
rid, fresh)`` at harvest granularity (de-duplicated across
replica-death re-routes via cumulative totals), ``("finish", rid,
tokens)``, ``("deadline_expired", rid, None)``, ``("cancelled", rid,
None)`` and ``("replica_death", rid, None)`` — the last for a SAMPLED
request that lost its replica mid-stream: replaying it elsewhere would
contradict tokens the client already holds, so it fails loudly with a
typed error instead.

**Health breaker** (:class:`BreakerConfig`): a typed per-replica state
machine ``healthy -> suspect -> dead -> probation`` layered over the
exception/hang death path.  ``suspect`` is the soft deadline — no
feed/step progress for ``suspect_after_s`` while holding work: the
replica takes no new assignments and its not-yet-admitted requests are
HEDGED onto a healthy peer (first admit wins, the loser is cancelled —
safe exactly because an unadmitted request has emitted nothing).
``dead`` is the breaker trip (exception or watchdog hang): flight
dump, outstanding work re-dispatched.  With ``revive=True`` the router
then probes for revival through the ReplicaSet's retained factory
(``grow``): the replacement enters ``probation`` — throttled to
``probation_inflight`` requests until ``probation_successes`` finish
clean, only then re-admitted to the full policy set.  A flapping
lineage (replacements dying in probation ``max_trips`` times in a row)
FREEZES revival: serving continues on the survivors, a human looks at
the flight records.
"""
from __future__ import annotations

import heapq
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.prefix_cache import ROOT_HASH, _chunk_hash
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.telemetry import flight, trace
from deepspeed_tpu.telemetry.metrics import metrics as _metrics

__all__ = ["Router", "POLICIES", "BreakerConfig", "RouterRejection",
           "QueueFullRejection", "ShedRejection",
           "NeverSchedulableRejection", "DeadlineRejection",
           "DrainingRejection", "REPLICA_STATES"]

REPLICA_STATES = ("healthy", "suspect", "dead", "probation")


class BreakerConfig:
    """Knobs for the replica health breaker (all optional).

    ``suspect_after_s``
        soft liveness deadline: a replica holding work whose
        ``last_progress`` is older than this turns ``suspect`` (no new
        assignments; unadmitted requests hedge to a peer).  0 disables
        suspect detection — the breaker then only reacts to hard
        trips.
    ``hedge``
        hedge a suspect's not-yet-admitted requests onto a healthy
        peer (exactly-once by construction: first admit wins, the
        loser is cancelled before it can emit).
    ``revive``
        after a trip, probe for revival by growing a replacement from
        the ReplicaSet's retained factory.  Off by default: spinning
        up an engine is expensive and only correct when the underlying
        fault is transient.
    ``probation_successes`` / ``probation_inflight``
        a revived replica must finish this many requests clean before
        re-admission, carrying at most ``probation_inflight`` at once.
    ``max_trips``
        consecutive probation deaths before revival FREEZES.
    """

    def __init__(self, suspect_after_s: float = 0.0, hedge: bool = True,
                 revive: bool = False, probation_successes: int = 2,
                 probation_inflight: int = 1, max_trips: int = 3) -> None:
        self.suspect_after_s = float(suspect_after_s)
        self.hedge = bool(hedge)
        self.revive = bool(revive)
        self.probation_successes = max(1, int(probation_successes))
        self.probation_inflight = max(1, int(probation_inflight))
        self.max_trips = max(1, int(max_trips))


class RouterRejection(RuntimeError):
    """Base of every typed router rejection."""


class QueueFullRejection(RouterRejection):
    """Every live replica is at its queue-depth cap."""


class ShedRejection(RouterRejection):
    """SLO burn rate above ``burn_shed``; low-priority load is shed."""


class NeverSchedulableRejection(RouterRejection):
    """The request could never be scheduled on any replica (prompt +
    budget beyond ``max_seq_len``, or KV pages beyond the combined
    tier capacity) — the engine's ``ValueError`` with a router type."""


class DeadlineRejection(RouterRejection):
    """The request's ``deadline_ms`` had already burned at submit —
    admitting it could only waste a slot on an answer nobody waits
    for."""


class DrainingRejection(RouterRejection):
    """The router is in graceful drain (``begin_drain``): in-flight
    requests finish, new ones are refused."""


class _RouterReq:
    __slots__ = ("rid", "prompt", "kw", "priority", "accept_t",
                 "affinity", "cost", "replica", "uid", "attempts",
                 "deadline_t", "cancelled", "streamed", "phase")

    def __init__(self, rid: int, prompt: np.ndarray, kw: Dict[str, Any],
                 priority: int, accept_t: float, affinity: int,
                 cost: int) -> None:
        self.rid = rid
        self.prompt = prompt
        self.kw = kw
        self.priority = priority
        self.accept_t = accept_t
        self.affinity = affinity
        self.cost = cost          # prompt + max_new token budget
        self.replica: Optional[str] = None
        self.uid: Optional[int] = None
        self.attempts = 0
        self.deadline_t: Optional[float] = None   # clock() expiry
        self.cancelled = False    # lazy heap removal marker
        self.streamed = 0         # generated tokens already emitted
        # disaggregated serving: "prefill"/"decode" classification when
        # a role split is active; None in fused mode (no role filter)
        self.phase: Optional[str] = None


# -- load-balancing policies ---------------------------------------------
# A policy picks one handle from the eligible candidates (alive, under
# the queue cap).  Sticky prefix affinity runs BEFORE the policy; the
# policy only sees requests with no (usable) affinity pin.

def _policy_rr(router: "Router", cands: List[Any], req: _RouterReq) -> Any:
    """Round-robin over the candidate list (per-dispatch counter)."""
    h = cands[router._rr % len(cands)]
    router._rr += 1
    return h


def _policy_least_tokens(router: "Router", cands: List[Any],
                         req: _RouterReq) -> Any:
    """Least outstanding token budget (prompt + max_new over every
    dispatched-but-unfinished request), router-side accounting only —
    deterministic and replica-thread-free."""
    return min(cands, key=lambda h: (router._tokens[h.name], h.idx))


def _policy_pressure(router: "Router", cands: List[Any],
                     req: _RouterReq) -> Any:
    """Least pool pressure (page occupancy + waiting queue), from each
    replica's last ``serving_stages()``-shape snapshot (taken on the
    replica thread, folded at join)."""
    return min(cands, key=lambda h: (router._pressure.get(h.name, 0.0),
                                     router._tokens[h.name], h.idx))


POLICIES: Dict[str, Callable[["Router", List[Any], _RouterReq], Any]] = {
    "rr": _policy_rr,
    "least_tokens": _policy_least_tokens,
    "pressure": _policy_pressure,
}


class Router:
    """Front-end over a :class:`~.replica_set.ReplicaSet` (or any list
    of handle-protocol objects — tests drive fakes).

    Parameters
    ----------
    replicas:
        ReplicaSet or list of handles.
    policy:
        ``"rr"`` | ``"least_tokens"`` | ``"pressure"`` (or a callable
        ``(router, candidates, request) -> handle``).
    slo:
        optional :class:`~deepspeed_tpu.telemetry.slo.SLOSet` watching
        router-level metrics (the router feeds ``router_e2e_ms`` per
        finished request); its worst-objective burn rate drives
        defer/shed.
    queue_cap:
        per-replica dispatched-but-unfinished cap; default
        ``2 * max_seqs`` of the first replica.
    burn_defer / burn_shed:
        burn-rate thresholds: ``>= burn_defer`` holds low-priority
        requests in the router queue; ``>= burn_shed`` rejects them at
        submit.  Priorities ``>= protected_priority`` bypass both.
    sticky:
        route requests sharing a page-aligned prompt prefix to the
        replica that saw the prefix first (prefix-cache affinity via
        the same chain hash the cache indexes with).
    """

    def __init__(self, replicas: Any, policy: str = "least_tokens",
                 slo: Any = None, queue_cap: Optional[int] = None,
                 burn_defer: float = 1.0, burn_shed: float = 2.0,
                 protected_priority: int = 1, sticky: bool = True,
                 clock: Callable[[], float] = time.perf_counter,
                 breaker: Optional[BreakerConfig] = None) -> None:
        self.handles: List[Any] = list(replicas)
        # retained when replicas is a ReplicaSet: the revival probe
        # grows replacements from its factory
        self._replica_set = replicas if hasattr(replicas, "grow") else None
        if not self.handles:
            raise ValueError("Router needs at least one replica")
        if callable(policy):
            self._policy, self.policy = policy, getattr(
                policy, "__name__", "custom")
        else:
            if policy not in POLICIES:
                raise ValueError(f"unknown router policy {policy!r} "
                                 f"(have {sorted(POLICIES)})")
            self._policy, self.policy = POLICIES[policy], policy
        self.slo = slo
        self.queue_cap = (int(queue_cap) if queue_cap is not None
                          else 2 * int(self.handles[0].max_seqs))
        if self.queue_cap < 1:
            raise ValueError("queue_cap must be >= 1")
        self.burn_defer = float(burn_defer)
        self.burn_shed = float(burn_shed)
        self.protected_priority = int(protected_priority)
        self.sticky = bool(sticky)
        self.clock = clock
        self._chunk = max(int(getattr(self.handles[0], "page_size", 64)), 1)

        self._rr = 0
        self._rid = 0
        self._heap: List[Tuple[int, int, _RouterReq]] = []   # (-pri, seq)
        self._hseq = 0
        self._live: Dict[int, _RouterReq] = {}               # accepted
        self._assigned: Dict[str, set] = {h.name: set()
                                          for h in self.handles}
        self._tokens: Dict[str, int] = {h.name: 0 for h in self.handles}
        self._pressure: Dict[str, float] = {}
        self._uid_rid: Dict[Tuple[str, int], int] = {}
        self._affinity: Dict[int, str] = {}                  # hash -> name
        self._outputs: Dict[int, np.ndarray] = {}
        self._draining = False
        self._retiring: set = set()
        self.accepting = True     # begin_drain() flips; submit refuses
        # event stream for streaming front ends: opt-in (a pump-only
        # caller would otherwise grow the list unboundedly)
        self.collect_events = False
        self._events: List[Tuple[str, int, Any]] = []
        self.stats_counters: Dict[str, int] = {
            "accepted": 0, "rejected_queue_full": 0, "rejected_shed": 0,
            "rejected_never_schedulable": 0, "rejected_deadline": 0,
            "rejected_draining": 0, "expired_deadline": 0,
            "cancelled": 0, "affinity_hits": 0,
            "rerouted": 0, "finished": 0, "replica_deaths": 0,
            "replicas_added": 0, "replicas_retired": 0,
            "sessions_handed_off": 0, "hedges": 0, "hedge_won": 0,
            "hedge_lost": 0, "failed_replica_death": 0, "revived": 0,
            "handoffs": 0, "handoff_kv": 0, "handoff_reprefill": 0}
        self._routed: Dict[str, int] = {h.name: 0 for h in self.handles}
        # -- health breaker state -----------------------------------------
        self.breaker = breaker
        self._health: Dict[str, str] = {}
        for h in self.handles:
            self._set_state(h.name, "healthy", announce=False)
        # rid -> {"orig", "target", "pending": {names with a put in
        # flight}} while a hedge is unresolved (first admit wins)
        self._hedges: Dict[int, Dict[str, Any]] = {}
        self._probation_left: Dict[str, int] = {}
        self._revive_pending = 0      # tripped replicas awaiting a probe
        self._revive_failures = 0     # consecutive probation deaths
        self.frozen = False           # revival frozen after max_trips
        # -- disaggregated serving (prefill/decode role split) ------------
        # name -> "prefill" | "decode"; empty = fused mode (every
        # replica does both, no handoffs).  set_roles() installs it.
        self._roles: Dict[str, str] = {}
        # prompt length (tokens) at which a request classifies as a
        # long prefill and is marked for prefill->decode handoff;
        # seeded to one page-aligned prefix chunk
        self.handoff_min_prompt = self._chunk
        self.handoff_depth = 2        # in-flight export rounds / prefill
        self.prefill_fraction = 0.5   # knob: share of prefill replicas
        self._handoff_inflight: Dict[str, int] = {}
        # rid -> {"src", "dst"} while the session blob is between the
        # export fold and the import fold (death-path bookkeeping)
        self._handoff_transit: Dict[int, Dict[str, str]] = {}

    # -- admission -------------------------------------------------------

    def _alive(self) -> List[Any]:
        return [h for h in self.handles if h.alive]

    def _dispatchable(self) -> List[Any]:
        """Alive, not mid-retire, not suspect: a retiring replica
        finishes its in-flight work but takes no new assignments; a
        suspect one proves liveness before getting more."""
        return [h for h in self.handles
                if h.alive and h.name not in self._retiring
                and self._health.get(h.name) != "suspect"]

    # -- disaggregated serving: prefill/decode role split -----------------

    def set_roles(self, roles: Dict[str, str]) -> None:
        """Install a replica role map for disaggregated serving:
        ``{name: "prefill" | "decode"}``.  Prefill-role replicas take
        long-prompt requests, run prefill + the first token, then hand
        the session (KV in spill format, donor digests riding along)
        to a decode-role replica; decode-role replicas take short-chat
        traffic directly plus the handed-off sessions.  An unnamed
        replica keeps serving both phases.  An empty map reverts to
        fused mode.  A non-empty map must name at least one replica of
        EACH role — a one-sided split would strand one traffic class.
        Install roles before traffic for clean phase-label attribution
        (the per-replica latency trackers re-label here)."""
        roles = {str(k): str(v) for k, v in roles.items()}
        have = {h.name for h in self.handles}
        unknown = set(roles) - have
        if unknown:
            raise ValueError(f"unknown replicas {sorted(unknown)} "
                             f"(have {sorted(have)})")
        bad = set(roles.values()) - {"prefill", "decode"}
        if bad:
            raise ValueError(f"unknown roles {sorted(bad)} "
                             "(want 'prefill' or 'decode')")
        if roles:
            vals = set(roles.values())
            if vals != {"prefill", "decode"}:
                raise ValueError(
                    "a role split needs at least one prefill AND one "
                    f"decode replica (got only {sorted(vals)})")
        self._roles = roles
        if roles:
            self.prefill_fraction = (
                sum(1 for v in roles.values() if v == "prefill")
                / len(roles))
        for h in self.handles:
            rl = getattr(getattr(h, "engine", None),
                         "request_latency", None)
            if rl is not None and hasattr(rl, "set_phase"):
                rl.set_phase(roles.get(h.name, ""))
        trace.event("router_roles", cat="serving",
                    prefill=",".join(sorted(
                        n for n, v in roles.items() if v == "prefill")),
                    decode=",".join(sorted(
                        n for n, v in roles.items() if v == "decode")))

    def set_prefill_fraction(self, frac: float) -> None:
        """Knob apply: re-derive the role map so about ``frac`` of the
        role-split replicas carry the prefill role (each role always
        keeps >= 1 replica).  Existing prefill replicas are kept
        prefill-side first — their prefix caches are warm.  A no-op in
        fused mode: the knob re-balances an existing split, it never
        creates one."""
        self.prefill_fraction = min(max(float(frac), 0.0), 1.0)
        if not self._roles:
            return
        names = [h.name for h in self.handles if h.name in self._roles]
        if len(names) < 2:
            return
        n_pre = min(max(int(round(self.prefill_fraction * len(names))),
                        1), len(names) - 1)
        pre_first = sorted(
            names, key=lambda n: (self._roles.get(n) != "prefill", n))
        new = {n: ("prefill" if i < n_pre else "decode")
               for i, n in enumerate(pre_first)}
        if new != self._roles:
            self.set_roles(new)

    def _role_ok(self, name: str, phase: Optional[str]) -> bool:
        """May replica ``name`` take a ``phase``-classified request?
        Trivially yes in fused mode, for unclassified requests, and
        for replicas outside the role map."""
        if not self._roles or phase is None:
            return True
        return self._roles.get(name, phase) == phase

    # -- health breaker ---------------------------------------------------

    def _by_name(self, name: str) -> Optional[Any]:
        return next((h for h in self.handles if h.name == name), None)

    def _set_state(self, name: str, state: str, why: str = "",
                   announce: bool = True) -> None:
        """One typed transition of the replica state machine: updates
        the ``dstpu_replica_state`` gauge (one-hot over states) and
        lands a ``cat="resilience"`` trace instant per decision."""
        prev = self._health.get(name)
        self._health[name] = state
        if _metrics.enabled:
            g = _metrics.gauge("dstpu_replica_state",
                               "Replica breaker state (one-hot)",
                               labels=("replica", "state"))
            for s in REPLICA_STATES:
                g.labels(replica=name, state=s).set(
                    1.0 if s == state else 0.0)
        if announce and trace.enabled and state != prev:
            event = {"healthy": "breaker_readmit",
                     "suspect": "breaker_suspect",
                     "dead": "breaker_trip",
                     "probation": "breaker_probation"}[state]
            trace.event(event, cat="resilience", replica=name,
                        prev=prev or "", why=why)

    def _check_health(self) -> None:
        """Soft-deadline sweep (runs each ``pump``): a replica holding
        work with stale ``last_progress`` turns suspect — excluded
        from dispatch, its unadmitted requests hedged; progress seen
        again re-admits it (the hedges resolve by admit race)."""
        cfg = self.breaker
        if cfg is None or cfg.suspect_after_s <= 0:
            return
        now = self.clock()
        for h in list(self.handles):
            if not h.alive:
                continue
            last = getattr(h, "last_progress", None)
            if last is None:
                continue          # handle without progress stamps
            state = self._health.get(h.name)
            stale = (self._assigned.get(h.name)
                     and now - last >= cfg.suspect_after_s)
            if stale and state == "healthy":
                self._set_state(h.name, "suspect",
                                why=f"no progress for "
                                    f"{now - last:.3f}s")
                if cfg.hedge:
                    self._hedge_from(h)
            elif not stale and state == "suspect":
                self._set_state(h.name, "healthy", why="progress resumed")

    def _hedge_from(self, h: Any) -> None:
        """Re-dispatch the suspect's not-yet-admitted requests on a
        healthy peer.  Exactly-once by construction: an unadmitted
        request has emitted nothing, and of the two in-flight puts the
        FIRST admit fold wins — the loser is cancelled at its own fold
        before the engine ever streams from it."""
        for rid in sorted(self._assigned.get(h.name, ())):
            req = self._live.get(rid)
            if (req is None or req.uid is not None or req.cancelled
                    or rid in self._hedges):
                continue
            cands = [x for x in self._dispatchable()
                     if x.name != h.name
                     and self._health.get(x.name) == "healthy"
                     and len(self._assigned[x.name]) < self.queue_cap]
            if not cands:
                return
            # policy directly — the affinity pin points at the suspect
            target = self._policy(self, cands, req)
            self._hedges[rid] = {"orig": h.name, "target": target.name,
                                 "pending": {h.name, target.name}}
            self.stats_counters["hedges"] += 1
            if _metrics.enabled:
                _metrics.counter("dstpu_hedge_total",
                                 "Hedged dispatches by outcome",
                                 labels=("outcome",)).labels(
                                     outcome="fired").inc()
            trace.event("hedge_fired", cat="resilience", replica=h.name,
                        target=target.name, rid=rid)
            self._send(req, target)

    def _maybe_revive(self) -> None:
        """Revival probe: grow one replacement per tripped replica from
        the ReplicaSet's retained factory and admit it ON PROBATION.
        Frozen (flapping lineage) or factory failure stops probing —
        survivors keep serving."""
        cfg = self.breaker
        if (cfg is None or not cfg.revive or self.frozen
                or self._revive_pending <= 0 or self._replica_set is None):
            return
        while self._revive_pending > 0 and not self.frozen:
            self._revive_pending -= 1
            trace.event("breaker_probe", cat="resilience",
                        replica="(new)", why="revival probe")
            try:
                (nh,) = self._replica_set.grow(1)
            except Exception as e:
                self._revive_failures += 1
                trace.event("breaker_probe_failed", cat="resilience",
                            replica="(new)", why=str(e)[:200])
                if self._revive_failures >= cfg.max_trips:
                    self._freeze("factory failed "
                                 f"{self._revive_failures}x")
                return
            self.add_replica(nh)
            self._set_state(nh.name, "probation", why="revival probe")
            self._probation_left[nh.name] = cfg.probation_successes
            self.stats_counters["revived"] += 1

    def _freeze(self, why: str) -> None:
        if self.frozen:
            return
        self.frozen = True
        trace.event("breaker_freeze", cat="resilience", replica="(all)",
                    why=why)
        flight.dump_on_fault(
            "breaker_freeze",
            RuntimeError(f"replica revival frozen: {why}"),
            extra={"revive_failures": self._revive_failures})

    def _max_burn(self) -> float:
        if self.slo is None:
            return 0.0
        state = self.slo.evaluate()
        return max((o["burn_rate"] for o in state.values()), default=0.0)

    def _prefix_hash(self, prompt: np.ndarray) -> int:
        """Chain hash over the page-aligned prompt prefix — the SAME
        chunking the prefix cache indexes with, so two prompts that
        would share cached pages land on the same replica."""
        n = (prompt.size // self._chunk) * self._chunk
        h = ROOT_HASH
        for i in range(0, n, self._chunk):
            h = _chunk_hash(h, tuple(int(t) for t in
                                     prompt[i:i + self._chunk]))
        return h

    def submit(self, prompt: Any, priority: int = 0,
               deadline_ms: Optional[float] = None, **kw) -> int:
        """Accept (or loudly reject) one request; returns the router
        request id.  ``kw`` passes through to the replica's
        ``put_request`` (max_new_tokens, eos_token_id, sampling...).
        ``deadline_ms`` is an ADMISSION input: already burned at
        submit raises :class:`DeadlineRejection`; burning while queued
        expires the request in the heap before it ever costs a slot."""
        if not self.accepting:
            self.stats_counters["rejected_draining"] += 1
            raise DrainingRejection(
                "router is draining (graceful shutdown): in-flight "
                "requests finish, new ones are refused")
        alive = self._alive()
        if not alive:
            raise RouterRejection("no live replicas")
        if deadline_ms is not None and float(deadline_ms) <= 0.0:
            self.stats_counters["rejected_deadline"] += 1
            raise DeadlineRejection(
                f"deadline_ms={float(deadline_ms):g} already burned "
                f"at submit")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = int(kw.get("max_new_tokens", 64))
        try:
            # replicas are homogeneous: one validation covers all
            alive[0].validate(prompt, max_new)
        except ValueError as e:
            self.stats_counters["rejected_never_schedulable"] += 1
            raise NeverSchedulableRejection(str(e)) from e
        if priority < self.protected_priority:
            burn = self._max_burn()
            if burn >= self.burn_shed:
                self.stats_counters["rejected_shed"] += 1
                raise ShedRejection(
                    f"SLO burn rate {burn:.2f} >= shed threshold "
                    f"{self.burn_shed:.2f}; priority {priority} below "
                    f"protected tier {self.protected_priority}")
        # accepted-but-unfinished (dispatched + still queued) against
        # the aggregate cap: a burst past every replica's queue depth
        # is rejected HERE, not silently parked in the router heap
        if len(self._live) >= self.queue_cap * len(alive):
            self.stats_counters["rejected_queue_full"] += 1
            raise QueueFullRejection(
                f"{len(self._live)} requests outstanding >= queue cap "
                f"{self.queue_cap} x {len(alive)} live replicas")
        rid = self._rid
        self._rid += 1
        req = _RouterReq(rid, prompt, dict(kw), int(priority),
                         self.clock(),
                         self._prefix_hash(prompt) if self.sticky
                         else ROOT_HASH,
                         int(prompt.size) + max_new)
        if deadline_ms is not None:
            req.deadline_t = req.accept_t + float(deadline_ms) / 1e3
        if self._roles:
            # classify: long prefills go to prefill-role replicas and
            # hand their finished KV to a decoder; short-chat requests
            # (and single-token ones, which finish at their prefill)
            # go straight to decode-role replicas
            req.phase = ("prefill"
                         if prompt.size >= self.handoff_min_prompt
                         else "decode")
            if req.phase == "prefill" and max_new > 1:
                req.kw["handoff"] = True
        self._live[rid] = req
        heapq.heappush(self._heap, (-req.priority, self._hseq, req))
        self._hseq += 1
        self.stats_counters["accepted"] += 1
        trace.event("router_accept", cat="serving", rid=rid,
                    priority=int(priority), prompt_len=int(prompt.size))
        return rid

    # -- dispatch --------------------------------------------------------

    def _pick(self, req: _RouterReq, cands: List[Any]) -> Any:
        if self.sticky and req.affinity != ROOT_HASH:
            pinned = self._affinity.get(req.affinity)
            if pinned is not None:
                for h in cands:
                    if h.name == pinned:
                        self.stats_counters["affinity_hits"] += 1
                        return h
        h = self._policy(self, cands, req)
        if self.sticky and req.affinity != ROOT_HASH:
            pinned = self._affinity.get(req.affinity)
            if (pinned is not None
                    and not self._role_ok(pinned, req.phase)):
                # the pin points across the role split (e.g. at a
                # replica re-roled to decode): re-home the chain to the
                # replica this request lands on, so later repeats of
                # the prefix hit a prefill replica that will own it
                self._affinity[req.affinity] = h.name
            else:
                self._affinity.setdefault(req.affinity, h.name)
        return h

    def _send(self, req: _RouterReq, h: Any) -> None:
        name = h.name
        self._assigned[name].add(req.rid)
        self._tokens[name] += req.cost
        self._routed[name] += 1
        req.replica = name
        req.attempts += 1
        with trace.span("router_dispatch", cat="serving", rid=req.rid,
                        replica=name):
            try:
                d = faults.hook("router.dispatch", replica=name,
                                rid=req.rid)
                if d is not None and d[0] in ("hang", "slow"):
                    time.sleep(float(d[1]))
                h.put_async(req.prompt, req.kw, req.accept_t,
                            on_done=lambda uid, r=req, hh=h:
                            self._on_admit(hh, r, uid))
            except Exception as e:       # join of an older op faulted
                self._on_replica_death(h, e)

    def _on_admit(self, h: Any, req: _RouterReq, uid: int) -> None:
        uid = int(uid)
        hedge = self._hedges.get(req.rid)
        if hedge is not None:
            # one of (up to) two racing puts for this rid just admitted;
            # the FIRST live fold wins, every other fold cancels its
            # copy and strips its claim — the engine that lost never
            # streams a token, so exactly-once holds by construction
            hedge["pending"].discard(h.name)
            if not hedge["pending"]:
                self._hedges.pop(req.rid, None)
            claimed = req.rid in self._assigned.get(h.name, set())
            won = (h.alive and claimed and req.uid is None
                   and req.rid in self._live and not req.cancelled)
            if not won:
                if h.alive:
                    self._cancel_on_replica(h, uid)
                if claimed:
                    self._assigned[h.name].discard(req.rid)
                    self._tokens[h.name] -= req.cost
                return
            req.uid = uid
            req.replica = h.name
            self._uid_rid[(h.name, uid)] = req.rid
            outcome = ("won" if h.name == hedge["target"] else "lost")
            self.stats_counters[f"hedge_{outcome}"] += 1
            if _metrics.enabled:
                _metrics.counter("dstpu_hedge_total",
                                 "Hedged dispatches by outcome",
                                 labels=("outcome",)).labels(
                                     outcome=outcome).inc()
            trace.event(f"hedge_{outcome}", cat="resilience",
                        replica=h.name, rid=req.rid)
            return
        if not h.alive:
            # a dead replica's feed window folding during close: the
            # request was already requeued — registering the stale uid
            # would resurrect a mapping the death path just severed
            return
        req.uid = uid
        if req.cancelled:
            # cancelled between dispatch and the admit fold: the uid
            # only just became known — propagate the teardown now
            self._cancel_on_replica(h, uid)
            return
        self._uid_rid[(h.name, uid)] = req.rid

    def _emit(self, kind: str, rid: int, payload: Any) -> None:
        if self.collect_events:
            self._events.append((kind, rid, payload))

    def poll_events(self) -> List[Tuple[str, int, Any]]:
        """Drain the event stream (``collect_events`` must be on):
        ``("tokens", rid, np.ndarray)`` / ``("finish", rid, tokens)``
        / ``("deadline_expired", rid, None)`` / ``("cancelled", rid,
        None)`` / ``("replica_death", rid, None)`` (a sampled request
        that lost its replica mid-stream — not replayable), in arrival
        order on the pump thread."""
        out, self._events = self._events, []
        return out

    def _dispatch_queued(self) -> int:
        """Send queued requests to replicas until the queue is empty,
        every replica is at cap, or SLO defer holds the remainder;
        returns the number dispatched.  Cancelled entries are skipped
        (lazy heap removal) and burned deadlines expire here — a
        request whose deadline passed while queued never costs a
        dispatch."""
        sent = 0
        burn = self._max_burn() if (self.slo is not None
                                    and not self._draining) else 0.0
        deferred: List[Tuple[int, int, _RouterReq]] = []
        while self._heap:
            req = self._heap[0][2]
            if req.cancelled:
                heapq.heappop(self._heap)
                continue
            if (req.deadline_t is not None
                    and self.clock() >= req.deadline_t):
                heapq.heappop(self._heap)
                self._live.pop(req.rid, None)
                self.stats_counters["expired_deadline"] += 1
                self._emit("deadline_expired", req.rid, None)
                trace.event("router_deadline_expired", cat="serving",
                            rid=req.rid, queued_ms=round(
                                (self.clock() - req.accept_t) * 1e3, 3))
                continue
            if (burn >= self.burn_defer and not self._draining
                    and req.priority < self.protected_priority):
                # deferred: held in the router queue (heap order puts
                # protected traffic first, so nothing above this is
                # waiting behind it)
                break
            cands = [h for h in self._dispatchable()
                     if len(self._assigned[h.name]) < self._cap(h.name)
                     and self._role_ok(h.name, req.phase)]
            if not cands:
                if self._roles and req.phase is not None:
                    # this request's role has no room, but the OTHER
                    # role may — park it aside so a full prefill side
                    # never head-of-line-blocks decode traffic (or
                    # vice versa)
                    deferred.append(heapq.heappop(self._heap))
                    continue
                break
            heapq.heappop(self._heap)
            self._send(req, self._pick(req, cands))
            sent += 1
        for entry in deferred:
            heapq.heappush(self._heap, entry)
        return sent

    def _cap(self, name: str) -> int:
        """Per-replica assignment cap: the queue cap, throttled to
        ``probation_inflight`` while the replica proves itself."""
        if (self.breaker is not None
                and self._health.get(name) == "probation"):
            return min(self.queue_cap, self.breaker.probation_inflight)
        return self.queue_cap

    # -- the serving loop ------------------------------------------------

    def pump(self) -> None:
        """One router round: health sweep + revival probe, dispatch
        what admission allows, then submit one step op per busy
        replica.  Results fold back on THIS thread at window joins
        (back-pressure, ``join_all`` or ``drain``)."""
        with trace.span("router_pump", cat="serving"):
            self._check_health()
            self._maybe_revive()
            self._dispatch_queued()
            self._pump_handoffs()
            for h in list(self.handles):
                if not h.alive:
                    continue
                if not self._assigned[h.name] and h.in_flight == 0:
                    continue
                try:
                    h.step_async(on_done=lambda payload, hh=h:
                                 self._on_step_done(hh, payload))
                except Exception as e:
                    self._on_replica_death(h, e)

    # -- prefill -> decode handoff ----------------------------------------

    def _pump_handoffs(self) -> None:
        """One export round per prefill-role replica that may hold a
        finished handoff prefill, bounded to ``handoff_depth`` export
        ops in flight per replica.  The export's fold picks a decode
        replica and chains the import (`_on_handoff_export`)."""
        if not self._roles:
            return
        for h in list(self.handles):
            if (not h.alive or h.name in self._retiring
                    or self._roles.get(h.name) != "prefill"
                    or getattr(h, "export_handoff_async", None) is None):
                continue
            if (self._handoff_inflight.get(h.name, 0)
                    >= max(int(self.handoff_depth), 1)):
                continue
            # only poke the replica while a handoff-marked request is
            # assigned there — the export op is not free, it occupies
            # a slot of the replica's feed window
            if not any(rq is not None and rq.kw.get("handoff")
                       for rq in (self._live.get(rid) for rid in
                                  self._assigned.get(h.name, ()))):
                continue
            self._handoff_inflight[h.name] = \
                self._handoff_inflight.get(h.name, 0) + 1
            t0 = self.clock()
            try:
                h.export_handoff_async(
                    on_done=lambda sessions, hh=h, t=t0:
                    self._on_handoff_export(hh, sessions, t))
            except Exception as e:   # join of an older op faulted
                self._handoff_inflight[h.name] = max(
                    self._handoff_inflight.get(h.name, 1) - 1, 0)
                self._on_replica_death(h, e)

    def _on_handoff_export(self, h: Any, sessions: List[Dict[str, Any]],
                           t0: float) -> None:
        """Export fold (router thread): route the finished-prefill
        session blobs to a decode-role replica and submit the import.
        Sessions are marked in-transit until the import folds — the
        death path knows which side of the wire still owns them."""
        self._handoff_inflight[h.name] = max(
            self._handoff_inflight.get(h.name, 1) - 1, 0)
        if not sessions:
            return
        t_exp = self.clock()
        cands = [x for x in self._dispatchable()
                 if x.name != h.name
                 and self._roles.get(x.name) == "decode"
                 and getattr(x, "import_handoff_async", None) is not None]
        # degenerate fallback (decode side died mid-flight): re-import
        # on the donor itself — the session decodes where it prefilled
        tgt = (min(cands, key=lambda x: (self._tokens[x.name], x.idx))
               if cands else h)
        for s in sessions:
            rid = self._uid_rid.get((h.name, int(s["uid"])))
            if rid is not None:
                self._handoff_transit[rid] = {"src": h.name,
                                              "dst": tgt.name}
        try:
            tgt.import_handoff_async(
                sessions, t_exp,
                on_done=lambda uids, hh=h, tt=tgt, ss=sessions,
                a=t0, b=t_exp:
                self._on_handoff_import(hh, tt, ss, uids, a, b))
        except Exception as e:       # join of an older op faulted
            for s in sessions:
                rid = self._uid_rid.get((h.name, int(s["uid"])))
                if rid is not None:
                    self._handoff_transit.pop(rid, None)
            self._on_replica_death(tgt, e)

    def _on_handoff_import(self, src: Any, tgt: Any,
                           sessions: List[Dict[str, Any]],
                           new_uids: List[int], t0: float,
                           t_exp: float) -> None:
        """Import fold (router thread): re-key each session's ledger
        entry from ``(src, old_uid)`` to ``(tgt, new_uid)`` — the same
        re-keying retire_replica does — and account the handoff path
        (KV payload vs degraded re-prefill).  Emits the
        ``cat="handoff"`` span quartet per session."""
        t_imp = self.clock()
        moved: List[Tuple[int, Optional[Dict[str, Any]]]] = []
        for s, new_uid in zip(sessions, new_uids):
            sp = s.get("spill")
            payload = None if sp is None else sp.get("payload")
            self.stats_counters["handoffs"] += 1
            self.stats_counters["handoff_kv" if payload is not None
                                else "handoff_reprefill"] += 1
            rid = self._uid_rid.pop((src.name, int(s["uid"])), None)
            if rid is None:
                # cancelled (or failed loudly) while in transit: tear
                # the freshly installed copy down on the receiver
                self._cancel_on_replica(tgt, int(new_uid))
                moved.append((-1, payload))
                continue
            self._handoff_transit.pop(rid, None)
            req = self._live.get(rid)
            self._uid_rid[(tgt.name, int(new_uid))] = rid
            self._assigned.get(src.name, set()).discard(rid)
            self._assigned[tgt.name].add(rid)
            if req is not None:
                if src.name in self._tokens:
                    self._tokens[src.name] -= req.cost
                self._tokens[tgt.name] += req.cost
                req.replica = tgt.name
                req.uid = int(new_uid)
                req.phase = "decode"
            moved.append((rid, payload))
        t_done = self.clock()
        if _metrics.enabled:
            fam = _metrics.counter("dstpu_handoff_total",
                                   "Prefill->decode handoffs by path",
                                   labels=("path",))
            n_kv = sum(1 for _, p in moved if p is not None)
            if n_kv:
                fam.labels(path="kv").inc(n_kv)
            if len(moved) - n_kv:
                fam.labels(path="reprefill").inc(len(moved) - n_kv)
            kv_bytes = sum(len(p["payload"]) for _, p in moved
                           if p is not None)
            if kv_bytes:
                _metrics.counter(
                    "dstpu_handoff_bytes_total",
                    "Handoff KV payload bytes moved").inc(kv_bytes)
        if trace.enabled:
            for rid, payload in moved:
                attrs = {"rid": int(rid), "src": src.name,
                         "dst": tgt.name}
                trace.add_complete("handoff_export", t0,
                                   max(t_exp - t0, 0.0),
                                   cat="handoff", **attrs)
                trace.add_complete(
                    "handoff_transfer", t_exp, max(t_imp - t_exp, 0.0),
                    cat="handoff",
                    bytes=(len(payload["payload"])
                           if payload is not None else 0), **attrs)
                trace.add_complete("handoff_import", t_imp,
                                   max(t_done - t_imp, 0.0),
                                   cat="handoff", **attrs)
                trace.add_complete(
                    "handoff_verify", t_imp, max(t_done - t_imp, 0.0),
                    cat="handoff",
                    pages=(int(payload["n_pages"])
                           if payload is not None else 0),
                    digests=bool(payload is not None
                                 and payload.get("digests")), **attrs)

    def _on_step_done(self, h: Any, payload: Any) -> None:
        # payload is (outs, pool, deltas); legacy fakes post (outs, pool)
        outs, pool = payload[0], payload[1]
        deltas = payload[2] if len(payload) > 2 else ()
        self._pressure[h.name] = float(pool.get("pressure", 0.0))
        for uid, new_toks, total, _done in deltas:
            rid = self._uid_rid.get((h.name, int(uid)))
            if rid is None:
                continue          # a re-routed request's stale copy
            req = self._live.get(rid)
            if req is None or int(total) <= req.streamed:
                continue          # re-route replay: already emitted
            fresh = new_toks[len(new_toks) - (int(total) - req.streamed):]
            req.streamed = int(total)
            self._emit("tokens", rid, np.asarray(fresh, np.int32))
        for uid, toks in outs:
            rid = self._uid_rid.pop((h.name, int(uid)), None)
            if rid is None:
                continue          # a re-routed request's stale copy
            req = self._live.pop(rid, None)
            if req is None:
                continue
            self._assigned[h.name].discard(rid)
            self._tokens[h.name] -= req.cost
            self._outputs[rid] = np.asarray(toks)
            self._emit("finish", rid, self._outputs[rid])
            self.stats_counters["finished"] += 1
            e2e_ms = (self.clock() - req.accept_t) * 1e3
            if self.slo is not None:
                self.slo.record("router_e2e_ms", e2e_ms)
            trace.event("router_finish", cat="serving", rid=rid,
                        replica=h.name, e2e_ms=round(e2e_ms, 3),
                        attempts=req.attempts)
            if self._health.get(h.name) == "probation":
                left = self._probation_left.get(h.name, 1) - 1
                self._probation_left[h.name] = left
                if left <= 0:
                    self._probation_left.pop(h.name, None)
                    self._revive_failures = 0
                    self._set_state(h.name, "healthy",
                                    why="probation complete")

    # -- cancellation + graceful drain -----------------------------------

    def _cancel_on_replica(self, h: Any, uid: int) -> None:
        """Propagate an engine-level cancel (slot teardown, page +
        tier release) to ``h``; best-effort on handles without the
        optional ``cancel_async`` op (older fakes)."""
        canceller = getattr(h, "cancel_async", None)
        if canceller is None or not h.alive:
            return
        try:
            canceller(uid, on_done=None)
        except Exception as e:    # join of an older op faulted
            self._on_replica_death(h, e)

    def cancel(self, rid: int) -> bool:
        """Cancel one accepted request (the front door's
        client-disconnect path): a queued request is lazily removed
        from the heap; a dispatched one is torn down on its replica
        (slot + pages + tiered spill state released mid-decode).
        Returns False when ``rid`` is unknown or already finished."""
        req = self._live.pop(rid, None)
        if req is None:
            return False
        req.cancelled = True
        self.stats_counters["cancelled"] += 1
        # mid-handoff: the popped _live entry (and the uid mapping
        # popped below) make the import fold cancel the fresh copy on
        # the receiver — the transit marker just needs clearing
        self._handoff_transit.pop(rid, None)
        if rid in self._hedges and req.uid is None:
            # two puts still race for this rid and neither has
            # admitted: each admit fold sees req.cancelled (or the
            # popped _live entry), cancels its copy and strips its own
            # claim — stripping here too would double-count
            pass
        elif req.replica is not None:
            self._assigned.get(req.replica, set()).discard(rid)
            if req.replica in self._tokens:
                self._tokens[req.replica] -= req.cost
            h = next((x for x in self.handles
                      if x.name == req.replica), None)
            if req.uid is not None:
                self._uid_rid.pop((req.replica, req.uid), None)
                if h is not None:
                    self._cancel_on_replica(h, req.uid)
            # uid still None: the admit fold hasn't run — _on_admit
            # sees req.cancelled and propagates then
        self._emit("cancelled", rid, None)
        trace.event("router_cancel", cat="serving", rid=rid,
                    dispatched=req.replica is not None)
        return True

    def begin_drain(self) -> None:
        """Graceful drain for rolling restarts: stop admitting (submit
        raises :class:`DrainingRejection`); in-flight and queued work
        keeps dispatching and finishing through ``pump``/``join``.
        The front door maps the rejection to 503 + Retry-After and
        hands prefix-cache-warm state over via ``retire_replica`` once
        in-flight streams finish."""
        if not self.accepting:
            return
        self.accepting = False
        trace.event("router_drain_begin", cat="serving",
                    outstanding=len(self._live), queued=len(self._heap))

    def _on_replica_death(self, h: Any, exc: BaseException) -> None:
        """Failure isolation — the breaker trip: mark the replica
        dead, dump the flight ring (the postmortem rides the span
        schema), and re-route its whole queue.  Full-prompt
        resubmission preserves greedy bit-parity on the survivors (the
        per-request ``streamed`` watermark suppresses the replayed
        prefix); a SAMPLED request that already streamed cannot be
        replayed without contradicting tokens the client holds, so it
        fails loudly as a ``replica_death`` event instead.  With
        revival enabled the trip also schedules a probe; a probation
        replica dying counts toward the flap freeze."""
        # dedup on the ROUTER's state machine, not the handle flag: a
        # hung handle marks itself dead (`_abandon_wedged`) before the
        # ReplicaHangError ever reaches us, and its orphans still need
        # requeueing exactly once
        if self._health.get(h.name) == "dead":
            return
        h.alive = False
        was_probation = self._health.get(h.name) == "probation"
        self._set_state(h.name, "dead", why=type(exc).__name__)
        self._probation_left.pop(h.name, None)
        self.stats_counters["replica_deaths"] += 1
        orphans = sorted(self._assigned[h.name])
        flight.dump_on_fault(
            f"replica_death_{h.name}", exc,
            extra={"replica": h.name,
                   "requeued_rids": orphans,
                   "policy": self.policy})
        # sessions in prefill->decode transit whose RECEIVER just died:
        # the blob is lost with it — fail or requeue from the full
        # prompt (these rids sit in the SOURCE's assigned set, so the
        # orphan loop below never sees them)
        for rid, tr in list(self._handoff_transit.items()):
            if tr["dst"] != h.name or tr["src"] == h.name:
                continue
            self._handoff_transit.pop(rid, None)
            req = self._live.get(rid)
            if req is None:
                continue
            src = tr["src"]
            if req.uid is not None:
                self._uid_rid.pop((src, req.uid), None)
            self._assigned.get(src, set()).discard(rid)
            if src in self._tokens:
                self._tokens[src] -= req.cost
            req.uid = None
            req.replica = None
            if req.streamed > 0 and req.kw.get("do_sample"):
                self._live.pop(rid, None)
                self.stats_counters["failed_replica_death"] += 1
                self._emit("replica_death", rid, None)
                trace.event("router_replica_death_fail", cat="serving",
                            rid=rid, streamed=int(req.streamed))
                continue
            self.stats_counters["rerouted"] += 1
            heapq.heappush(self._heap, (-req.priority, self._hseq, req))
            self._hseq += 1
        for rid in orphans:
            tr = self._handoff_transit.get(rid)
            if (tr is not None and tr["src"] == h.name
                    and tr["dst"] != h.name):
                # the session blob already left this replica: the
                # in-flight import on tr["dst"] will claim the rid at
                # its fold — requeueing here would run it twice
                continue
            req = self._live.get(rid)
            if req is None:
                continue
            self._handoff_transit.pop(rid, None)
            if req.uid is not None:
                self._uid_rid.pop((h.name, req.uid), None)
            self._tokens[h.name] -= req.cost
            if req.replica is not None and req.replica != h.name:
                continue          # the hedge's other copy owns it
            hedge = self._hedges.get(rid)
            if hedge is not None:
                other = (hedge["target"] if h.name == hedge["orig"]
                         else hedge["orig"])
                oh = self._by_name(other)
                if (oh is not None and oh.alive
                        and other in hedge["pending"]):
                    # the surviving copy's admit fold will claim it
                    hedge["pending"].discard(h.name)
                    req.uid = None
                    req.replica = None
                    continue
                self._hedges.pop(rid, None)
            req.uid = None
            req.replica = None
            if req.streamed > 0 and req.kw.get("do_sample"):
                # replaying a sampled request elsewhere would emit a
                # DIFFERENT continuation after tokens the client
                # already consumed — fail it loudly and exactly once
                self._live.pop(rid, None)
                self.stats_counters["failed_replica_death"] += 1
                self._emit("replica_death", rid, None)
                trace.event("router_replica_death_fail", cat="serving",
                            rid=rid, streamed=int(req.streamed))
                continue
            self.stats_counters["rerouted"] += 1
            heapq.heappush(self._heap, (-req.priority, self._hseq, req))
            self._hseq += 1
        self._assigned[h.name] = set()
        # affinity pins to a dead replica would strand their chains
        for k in [k for k, v in self._affinity.items() if v == h.name]:
            del self._affinity[k]
        self._handoff_inflight.pop(h.name, None)
        if self._roles.pop(h.name, None) is not None:
            vals = set(self._roles.values())
            if vals != {"prefill", "decode"}:
                # the split lost one whole side: fall back to fused
                # routing so the surviving role's traffic cannot be
                # stranded behind an empty candidate set
                self._roles = {}
                for rq in self._live.values():
                    rq.phase = None
        try:
            h.close()
        except Exception:
            pass
        cfg = self.breaker
        if cfg is not None and cfg.revive:
            if was_probation:
                self._revive_failures += 1
                if self._revive_failures >= cfg.max_trips:
                    self._freeze(f"replacement died in probation "
                                 f"{self._revive_failures}x in a row")
            if not self.frozen:
                self._revive_pending += 1
        if (not self._alive() and (self._heap or self._live)
                and self._revive_pending <= 0):
            raise RouterRejection(
                "all replicas dead with requests outstanding") from exc

    # -- elasticity: live grow / shrink ----------------------------------
    # The serving half of elastic re-slicing: replicas join and leave a
    # RUNNING router.  Growth admits a fresh handle (optionally prefix-
    # warmed from a donor so sticky chains hit on arrival); retirement
    # drains a replica without dropping work — parked sessions travel to
    # a survivor in spill format, in-flight requests finish in place,
    # and affinity pins re-home.

    def add_replica(self, handle: Any, warm_from: Any = None,
                    warm_limit: int = 8) -> None:
        """Admit ``handle`` to the routed set.  ``warm_from`` names a
        donor handle whose prefix-cache chains are replayed on the new
        replica first (up to ``warm_limit`` longest chains), so sticky
        traffic re-pinned here starts warm instead of cold."""
        if any(h.name == handle.name for h in self.handles):
            raise ValueError(f"replica {handle.name!r} already routed")
        warmed = 0
        if warm_from is not None:
            warmed = self._warm_from(handle, warm_from, warm_limit)
        self.handles.append(handle)
        self._assigned[handle.name] = set()
        self._tokens[handle.name] = 0
        self._routed[handle.name] = 0
        self._set_state(handle.name, "healthy", announce=False)
        self.stats_counters["replicas_added"] += 1
        trace.event("router_grow", cat="control", replica=handle.name,
                    warmed_chains=warmed, replicas=len(self.handles))

    def _warm_from(self, handle: Any, donor: Any, limit: int) -> int:
        """Replay the donor's longest cached prefix chains as 1-token
        generations on the new replica (outputs discarded) — the new
        prefix cache ends up holding the same chains the donor's sticky
        pins reference.  Best-effort: a donor without a prefix cache
        (or with none populated) warms nothing."""
        pfx = getattr(getattr(donor, "engine", None), "_pfx", None)
        entries = getattr(pfx, "_entries", None)
        if not entries:
            return 0
        parents = {e.parent for e in entries.values()}
        chains: List[List[int]] = []
        for key, ent in entries.items():
            if key in parents:
                continue          # interior node — a longer chain covers it
            toks: List[int] = []
            cur, ok = ent, True
            while True:
                toks[:0] = cur.tokens
                if cur.parent == ROOT_HASH:
                    break
                cur = entries.get(cur.parent)
                if cur is None:   # chain broken mid-walk (evicted link)
                    ok = False
                    break
            if ok and toks:
                chains.append(toks)
        chains.sort(key=len, reverse=True)
        warmed = 0
        for toks in chains[:max(int(limit), 0)]:
            p = np.asarray(toks, np.int32)
            try:
                handle.validate(p, 1)
            except ValueError:
                continue          # chain outgrew the new replica's limits
            handle.put_async(p, {"max_new_tokens": 1}, self.clock(),
                             on_done=None)
            warmed += 1
        if warmed:
            handle.drain_async(on_done=None)
            handle.join_all()     # discard warm-up outputs
        return warmed

    def retire_replica(self, name: str,
                       target: Optional[str] = None) -> Dict[str, Any]:
        """Drain ``name`` out of the routed set without losing work:
        stop routing to it, hand its parked sessions (waiting queue,
        spilled KV travelling in spill format with donor digests) to a
        survivor, finish its in-flight requests in place, migrate its
        affinity pins, then close and remove it.  Returns a summary
        dict; raises :class:`RouterRejection` when no survivor exists."""
        h = next((x for x in self.handles if x.name == name), None)
        if h is None:
            raise ValueError(f"unknown replica {name!r}")
        survivors = [x for x in self.handles
                     if x.alive and x.name != name
                     and x.name not in self._retiring]
        if not survivors:
            raise RouterRejection(
                f"cannot retire {name!r}: no surviving replica "
                f"to absorb its sessions")
        if target is not None:
            tgt = next((x for x in survivors if x.name == target), None)
            if tgt is None:
                raise ValueError(f"target {target!r} is not a live, "
                                 f"non-retiring survivor")
        else:
            tgt = min(survivors,
                      key=lambda x: (self._tokens[x.name], x.idx))
        self._retiring.add(name)
        handed_off = 0
        try:
            # 1. parked-session handoff: settle queued admits so every
            # waiting request has a uid, then export the waiting queue
            sessions: List[Dict[str, Any]] = []
            exporter = getattr(h, "export_parked_async", None)
            if h.alive and exporter is not None:
                try:
                    h.join_all()
                    box: List[Any] = []
                    exporter(on_done=box.append)
                    h.join_all()
                    sessions = box[0] if box else []
                except Exception as e:
                    self._on_replica_death(h, e)
                    sessions = []
            if sessions and tgt.alive:
                nbox: List[Any] = []
                tgt.import_parked_async(sessions, on_done=nbox.append)
                tgt.join_all()
                new_uids = nbox[0] if nbox else []
                for s, new_uid in zip(sessions, new_uids):
                    rid = self._uid_rid.pop((name, int(s["uid"])), None)
                    if rid is None:
                        continue
                    req = self._live.get(rid)
                    self._uid_rid[(tgt.name, int(new_uid))] = rid
                    self._assigned[name].discard(rid)
                    self._assigned[tgt.name].add(rid)
                    if req is not None:
                        self._tokens[name] -= req.cost
                        self._tokens[tgt.name] += req.cost
                        req.replica = tgt.name
                        req.uid = int(new_uid)
                    handed_off += 1
            # 2. finish the retiring replica's in-flight work in place
            # (it takes no new assignments — _dispatchable excludes it)
            while h.alive and self._assigned.get(name):
                self.pump()
                self.join()
        finally:
            self._retiring.discard(name)
        # 3. re-home sticky pins so chains follow the sessions
        moved_pins = 0
        for k in [k for k, v in self._affinity.items() if v == name]:
            self._affinity[k] = tgt.name
            moved_pins += 1
        try:
            h.close()
        except Exception:
            pass
        self.handles = [x for x in self.handles if x.name != name]
        self._assigned.pop(name, None)
        self._tokens.pop(name, None)
        self._pressure.pop(name, None)
        self._health.pop(name, None)
        self._probation_left.pop(name, None)
        self._handoff_inflight.pop(name, None)
        if self._roles.pop(name, None) is not None:
            vals = set(self._roles.values())
            if vals != {"prefill", "decode"}:
                self._roles = {}    # split lost a side: fused fallback
                for rq in self._live.values():
                    rq.phase = None
        self.stats_counters["replicas_retired"] += 1
        self.stats_counters["sessions_handed_off"] += handed_off
        trace.event("router_shrink", cat="control", replica=name,
                    target=tgt.name, handed_off=handed_off,
                    moved_pins=moved_pins, replicas=len(self.handles))
        return {"replica": name, "target": tgt.name,
                "handed_off": handed_off, "moved_pins": moved_pins}

    def join(self) -> None:
        """Fold every outstanding replica op (blocking)."""
        for h in list(self.handles):
            if not h.alive:
                continue
            try:
                h.join_all()
            except Exception as e:
                self._on_replica_death(h, e)

    def drain(self) -> Dict[int, np.ndarray]:
        """Run until every accepted request finishes (deferred ones
        included — drain dispatches regardless of burn rate); returns
        ``{rid: tokens}`` for everything not yet collected."""
        self._draining = True
        try:
            while self._heap or self._live:
                self.pump()
                self.join()
        finally:
            self._draining = False
        return self.get_outputs()

    def get_outputs(self) -> Dict[int, np.ndarray]:
        out, self._outputs = self._outputs, {}
        return out

    def close(self) -> None:
        for h in self.handles:
            try:
                h.close()
            except Exception:
                pass

    # -- observability ---------------------------------------------------

    @property
    def queued(self) -> int:
        return len(self._heap)

    @property
    def outstanding(self) -> int:
        """Accepted requests not yet finished (queued + dispatched)."""
        return len(self._live)

    def stats(self) -> Dict[str, Any]:
        """Flat router summary for the example printout / smoke gate."""
        out: Dict[str, Any] = {"policy": self.policy,
                               "queue_cap": self.queue_cap,
                               "replicas": len(self.handles),
                               "replicas_alive": len(self._alive()),
                               "queued": len(self._heap),
                               "in_flight": len(self._live)}
        out.update(self.stats_counters)
        if self.breaker is not None:
            out["frozen"] = self.frozen
        if self._roles:
            out["prefill_fraction"] = round(self.prefill_fraction, 4)
            out["handoffs_in_transit"] = len(self._handoff_transit)
        for h in self.handles:
            out[f"routed_{h.name}"] = self._routed[h.name]
            out[f"outstanding_tokens_{h.name}"] = self._tokens[h.name]
            out[f"state_{h.name}"] = self._health.get(h.name, "healthy")
            if h.name in self._roles:
                out[f"role_{h.name}"] = self._roles[h.name]
            if h.name in self._pressure:
                out[f"pressure_{h.name}"] = self._pressure[h.name]
        if self.slo is not None:
            out["burn_rate_max"] = round(self._max_burn(), 4)
        return out
