"""Replica execution layer: one ragged engine per single-worker thread,
fed through a bounded async window.

The handle is deliberately thin — the :class:`~.router.Router` owns all
routing state (assignment, outstanding-token accounting, pressure
snapshots) and talks to a handle only through the small protocol below,
so router unit tests drive fake replicas with scripted behaviour and no
threads:

- ``alive`` / ``name`` / ``max_seqs`` / ``page_size``
- ``validate(prompt, max_new)`` — the engine's submit-time
  schedulability check (raises ``ValueError``)
- ``put_async(prompt, kw, accept_t, on_done)`` — enqueue a request on
  the replica thread; ``on_done(uid)`` runs at join time on the
  ROUTER thread
- ``step_async(on_done)`` — one engine iteration + output collection;
  ``on_done((outputs, pool, deltas))`` at join time (the router also
  accepts the legacy ``(outputs, pool)`` shape from older fakes)
- ``cancel_async(uid, on_done)`` — OPTIONAL: cancel a request at any
  lifecycle stage on the replica thread (the front door's
  client-disconnect path); routers probe with ``getattr``
- ``join_all()`` — drain the feed window (folds every pending
  ``on_done``; re-raises the first replica fault after the sweep)
- ``drain_async(on_done)`` / ``close()`` — shutdown halves

Every op rides the handle's :class:`BoundedAsyncStage` feed window
(waiter = ``Future.result`` — the third instance of the substrate,
after the engine's pipelined decode carry and the NVMe moment stream):
the window bounds router run-ahead per replica and serializes
``on_done`` folds onto whichever thread joins (the router's), so
router state never needs a lock.

**Liveness watchdog** (``watchdog_s > 0``): the production replica
failure is not an exception but a WEDGE — a stuck decode, a deadlocked
AIO wait — which today's exception-driven death path never sees (a
hung op's future simply never resolves, so ``join_all`` blocks
forever).  Armed, every window join waits at most ``watchdog_s`` for
the op to make progress (the ``comm/watchdog.py`` heartbeat pattern
applied per replica): on expiry the wedged worker thread is abandoned
(``shutdown(wait=False)`` — a blocked engine step cannot be
interrupted from Python), the window's unresolved ops are written off,
and the join raises :class:`ReplicaHangError`, which the router's
existing death path turns into a breaker trip + re-dispatch.
``last_progress`` is stamped at every successful join — the router's
suspect detection (soft deadline, hedging) reads it without touching
the replica thread.  Disarmed (the default) the waiter is a plain
``Future.result`` — zero overhead on the fault-free path.
"""
from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.telemetry import trace
from deepspeed_tpu.telemetry.metrics import metrics as _metrics
from deepspeed_tpu.utils.async_stage import BoundedAsyncStage, StageTimers

__all__ = ["EngineReplicaHandle", "ReplicaHangError", "ReplicaSet"]


class ReplicaHangError(RuntimeError):
    """A replica op blew the liveness watchdog deadline: the worker
    thread is wedged (stuck decode / AIO / feed deadlock) and has been
    abandoned.  The router treats this exactly like a replica death —
    flight dump, breaker trip, outstanding work re-dispatched."""


def _future_result(fut: Future) -> Any:
    return fut.result()


class EngineReplicaHandle:
    """One ragged engine bound to its own single-worker executor.

    The single worker is the whole concurrency story: ops submitted to
    a handle execute in submission order on the replica's thread (the
    engine is never touched from two threads), while DIFFERENT replicas
    overlap freely.  The feed window bounds how many ops the router may
    have outstanding per replica (``feed_depth``); past the bound a
    submit first joins the oldest op, which is also where completed
    results fold back into the router.
    """

    def __init__(self, idx: int, engine: Any, feed_depth: int = 2,
                 name: Optional[str] = None, watchdog_s: float = 0.0,
                 clock: Callable[[], float] = time.perf_counter) -> None:
        self.idx = int(idx)
        self.name = name if name is not None else f"r{idx}"
        self.engine = engine
        # stamp the replica identity into the engine's metric emitters
        # (dstpu_request_* / dstpu_serving_stage_seconds children get a
        # replica label so export_text() distinguishes replicas)
        engine.set_replica(self.name)
        self.alive = True
        self.watchdog_s = float(watchdog_s)
        self.hung = False
        self._clock = clock
        self.last_progress = clock()
        self._timers = StageTimers(cat="serving")
        self._window = BoundedAsyncStage(
            waiter=self._wd_result, depth=feed_depth,
            timers=self._timers, name=f"replica_feed_{self.name}")
        self._pool: Optional[ThreadPoolExecutor] = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"dstpu-replica-{self.name}")
        self._seq = 0

    def _wd_result(self, fut: Future) -> Any:
        """Window waiter: joins one replica op, stamping
        ``last_progress`` (the router's suspect detector reads it).
        With the watchdog armed the join waits at most ``watchdog_s``;
        expiry abandons the wedged worker and raises
        :class:`ReplicaHangError` on the caller's thread — the
        router's — so the breaker trips synchronously."""
        if self.watchdog_s <= 0:
            res = fut.result()
        else:
            try:
                res = fut.result(timeout=self.watchdog_s)
            except _FutureTimeout:
                self._abandon_wedged()
                raise ReplicaHangError(
                    f"replica {self.name} made no feed/step progress "
                    f"within the {self.watchdog_s:.1f}s watchdog deadline "
                    f"(wedged decode/AIO/feed thread) — worker abandoned, "
                    f"replica tripped dead") from None
        self.last_progress = self._clock()
        return res

    def _abandon_wedged(self) -> None:
        """The worker thread is wedged inside an op and cannot be
        interrupted from Python: abandon the pool, write off every
        unresolved window op (their futures may never complete), and
        mark the handle dead so no further submits land."""
        self.hung = True
        self.alive = False
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        dropped = self._window.abandon()
        if trace.enabled:
            trace.event("replica_hang", cat="resilience",
                        replica=self.name, deadline_s=self.watchdog_s,
                        abandoned_ops=int(dropped))
        if _metrics.enabled:
            _metrics.counter(
                "dstpu_watchdog_timeouts_total",
                "Watchdog deadline fires (collective + replica feed)",
                labels=("what",)).labels(
                    what=f"replica_{self.name}").inc()

    # -- protocol surface (what fakes implement) -------------------------

    @property
    def max_seqs(self) -> int:
        return int(self.engine.max_seqs)

    @property
    def page_size(self) -> int:
        return int(self.engine.page_size)

    @property
    def in_flight(self) -> int:
        return self._window.in_flight

    def validate(self, prompt: Any, max_new: int) -> None:
        self.engine.validate_request(prompt, max_new)

    def put_async(self, prompt: Any, kw: Dict[str, Any], accept_t: float,
                  on_done: Callable[[int], Any]) -> None:
        eng = self.engine

        def op() -> int:
            uid = eng.put_request(prompt, **kw)
            # router accept -> replica admit lands as its own series
            # (router_queue_wait_ms), never folded into TTFT
            eng.request_latency.note_router_accept(uid, accept_t)
            return uid

        self._submit(op, on_done)

    def step_async(self, on_done: Callable[[Any], Any]) -> None:
        """One engine iteration; the payload handed to ``on_done`` is
        ``(outputs, pool, deltas)`` where ``outputs`` is the engine's
        ``get_outputs()`` list, ``pool`` a lightweight pressure
        snapshot taken ON the replica thread (the router never reads
        engine state across threads), and ``deltas`` the engine's
        ``stream_deltas()`` — fresh tokens at harvest granularity for
        streaming front ends.  The router also accepts the legacy
        2-tuple payload (test fakes)."""
        eng = self.engine
        name = self.name

        def op() -> Tuple[List[Tuple[int, Any]], Dict[str, Any],
                          List[Tuple[int, List[int], int, bool]]]:
            # chaos sites, ON the replica thread: replica.step raises
            # (crash/io_error -> the exception death path), replica.hang
            # honors hang/slow directives by wedging right here — the
            # future never resolves until the sleep ends, which is
            # exactly the failure the watchdog exists to bound
            faults.hook("replica.step", replica=name)
            d = faults.hook("replica.hang", replica=name)
            if d is not None and d[0] in ("hang", "slow"):
                time.sleep(float(d[1]))
            if eng.has_work():
                eng.step()
            deltas = eng.stream_deltas()   # before get_outputs: a
            outs = eng.get_outputs()       # collected uid drops its cursor
            return outs, self._pool_snapshot(eng), deltas

        self._submit(op, on_done)

    def cancel_async(self, uid: int,
                     on_done: Optional[Callable[[Any], Any]] = None
                     ) -> None:
        """Cancel ``uid`` on the replica thread at whatever lifecycle
        stage it is in (queued / spilled / mid-decode / LC-parked);
        ``on_done(stage_or_None)`` at join time."""
        eng = self.engine
        self._submit(lambda: eng.cancel(uid), on_done)

    def drain_async(self, on_done: Callable[[Any], Any]) -> None:
        """Run the replica to completion (shutdown half)."""
        eng = self.engine

        def op() -> Tuple[List[Tuple[int, Any]], Dict[str, Any]]:
            outs = list(eng.drain().items())
            return outs, self._pool_snapshot(eng)

        self._submit(op, on_done)

    def export_parked_async(self, on_done: Callable[[Any], Any]) -> None:
        """Pull the engine's parked sessions (spill-format blobs) off
        the replica thread — the shrink half of elastic re-slicing;
        ``on_done(sessions)`` at join time."""
        eng = self.engine

        def op() -> List[Dict[str, Any]]:
            return eng.export_parked()

        self._submit(op, on_done)

    def import_parked_async(self, sessions: List[Dict[str, Any]],
                            on_done: Callable[[Any], Any]) -> None:
        """Install handed-off sessions on this replica's thread;
        ``on_done(new_uids)`` at join time (the router re-keys its
        uid ledger with them)."""
        eng = self.engine

        def op() -> List[int]:
            return eng.import_parked(sessions)

        self._submit(op, on_done)

    def export_handoff_async(self, on_done: Callable[[Any], Any]) -> None:
        """Pull the engine's handoff-ready sessions (prefill + first
        token done, KV in spill format) off the replica thread — the
        prefill-role half of disaggregated serving; ``on_done(sessions)``
        at join time."""
        eng = self.engine

        def op() -> List[Dict[str, Any]]:
            return eng.export_handoff()

        self._submit(op, on_done)

    def import_handoff_async(self, sessions: List[Dict[str, Any]],
                             export_t: float,
                             on_done: Callable[[Any], Any]) -> None:
        """Install handed-off prefill sessions on this (decode-role)
        replica's thread; the engine stamps ``export_t -> now`` as each
        request's handoff stall.  ``on_done(new_uids)`` at join time —
        the router re-keys its uid ledger with them."""
        eng = self.engine

        def op() -> List[int]:
            return eng.import_handoff(sessions, export_t)

        self._submit(op, on_done)

    def join_all(self) -> None:
        """Fold every pending op (its ``on_done`` runs here, on the
        caller's thread); first replica fault re-raises after the
        sweep — the substrate's drain contract."""
        self._window.drain()

    def close(self) -> None:
        """Idempotent teardown: abandon the window (faults already
        handled or about to be surfaced elsewhere), stop the worker,
        release engine resources.  A HUNG handle's window is written
        off instead of drained — its futures may never resolve and
        joining them would wedge the caller too."""
        self.alive = False
        if self.hung:
            self._window.abandon()
        else:
            try:
                self._window.drain()
            except Exception:
                pass              # a dead replica's pending ops may raise
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        try:
            self.engine.close()
        except Exception:
            pass

    # -- internals -------------------------------------------------------

    @staticmethod
    def _pool_snapshot(eng: Any) -> Dict[str, Any]:
        usable = max(eng.num_pages - 1, 1)
        in_use = usable - eng.allocator.free_pages
        return {"pages_in_use": int(in_use),
                "waiting_requests": len(eng.waiting),
                "pressure": round(in_use / usable + len(eng.waiting), 4)}

    def _submit(self, fn: Callable[[], Any],
                on_done: Optional[Callable[[Any], Any]]) -> None:
        if not self.alive or self._pool is None:
            raise RuntimeError(f"replica {self.name} is not alive")
        key = self._seq
        self._seq += 1
        self._window.submit(key, self._pool.submit(fn), on_done=on_done)

    def feed_stats(self) -> Dict[str, Any]:
        """Window counters/timers (``submitted``/``completed`` +
        ``submit_wait_s``) for the router stats printout."""
        return self._timers.snapshot()


class ReplicaSet:
    """N data-parallel replicas built from ``factory(i) -> engine``.

    On the CPU tier-1 path every engine shares the host platform
    (thread-per-replica; start the process with
    ``--xla_force_host_platform_device_count=N`` in ``XLA_FLAGS`` when
    real device overlap is wanted); on TPU the factory places each
    replica's params/cache on its own mesh slice and the engine's
    existing GSPMD annotations shard WITHIN the slice — replica data
    parallelism composes with in-replica tensor parallelism without
    the router knowing either exists.
    """

    def __init__(self, factory: Callable[[int], Any], n: int,
                 feed_depth: int = 2, watchdog_s: float = 0.0) -> None:
        if n < 1:
            raise ValueError("ReplicaSet needs n >= 1 replicas")
        # retained: grow() builds new replicas from the same factory
        self._factory = factory
        self._feed_depth = int(feed_depth)
        self._watchdog_s = float(watchdog_s)
        self._next_idx = 0
        self.handles: List[EngineReplicaHandle] = []
        try:
            for _ in range(int(n)):
                self._spawn()
        except Exception:
            self.close()          # don't leak half-built replica threads
            raise

    def _spawn(self) -> EngineReplicaHandle:
        i = self._next_idx
        self._next_idx += 1       # indices (and names) are never reused
        h = EngineReplicaHandle(i, self._factory(i),
                                feed_depth=self._feed_depth,
                                watchdog_s=self._watchdog_s)
        self.handles.append(h)
        return h

    def grow(self, n: int = 1) -> List[EngineReplicaHandle]:
        """Build ``n`` new replicas from the retained factory (fresh,
        never-reused indices/names) and return their handles.  The
        handles are NOT yet routed — the caller admits each via
        ``Router.add_replica`` (optionally prefix-warmed) once it is
        ready for traffic."""
        made: List[EngineReplicaHandle] = []
        try:
            for _ in range(int(n)):
                made.append(self._spawn())
        except Exception:
            for h in made:
                self.handles.remove(h)
                h.close()
            raise
        return made

    def shrink(self, names) -> List[EngineReplicaHandle]:
        """Remove (and close) replicas by name.  The router retires a
        replica FIRST — drain + parked-session handoff — so the close
        here is an idempotent resource release, never a request drop.
        Refuses to empty the set."""
        names = {names} if isinstance(names, str) else set(names)
        have = {h.name for h in self.handles}
        unknown = names - have
        if unknown:
            raise ValueError(f"unknown replicas {sorted(unknown)} "
                             f"(have {sorted(have)})")
        if len(self.handles) - len(names) < 1:
            raise ValueError("shrink would leave an empty replica set")
        dropped = [h for h in self.handles if h.name in names]
        self.handles = [h for h in self.handles if h.name not in names]
        for h in dropped:
            h.close()
        return dropped

    def __len__(self) -> int:
        return len(self.handles)

    def __iter__(self):
        return iter(self.handles)

    def __getitem__(self, i: int) -> EngineReplicaHandle:
        return self.handles[i]

    @property
    def alive(self) -> List[EngineReplicaHandle]:
        return [h for h in self.handles if h.alive]

    def close(self) -> None:
        for h in self.handles:
            h.close()
