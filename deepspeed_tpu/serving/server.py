"""Network front door: stdlib-asyncio HTTP/1.1 + SSE endpoint over the
:class:`~.router.Router` — token streaming, cancellation, deadlines,
graceful drain.

Two threads, each owning exactly one world:

- the **asyncio loop thread** owns every socket: accept, parse, SSE
  writes, disconnect detection.  It never touches the router.
- the **router pump thread** owns ALL router state (the router's
  single-threaded-by-construction contract): it drains a command queue
  (submit / cancel / drain), runs ``pump``/``join`` rounds, and
  forwards the router's event stream.

Commands cross asyncio -> pump on a thread-safe ``queue.Queue``;
results and token events cross back via ``loop.call_soon_threadsafe``
into per-request ``asyncio.Queue``s, so tokens stream at HARVEST
granularity (the engine's deferred-harvest folding grain) with no
locks anywhere near engine or router state.

Capabilities the library layer cannot express:

- **client-disconnect cancellation**: an EOF watcher on every stream
  turns a vanished client into ``Router.cancel`` -> engine
  ``cancel(uid)`` — slot teardown, page refcount release, tiered-spill
  cleanup mid-decode, audit-clean under prefix-COW sharing.
- **deadlines as admission input**: ``deadline_ms`` rides into the
  router's typed admission (burned -> 429 ``DeadlineRejection``;
  expiring while queued -> SSE ``error`` event, never a slot).
- **graceful drain**: SIGTERM (``install_signal_handlers``) stops
  admission (503 + Retry-After), finishes every in-flight stream with
  zero dropped tokens, then runs the optional ``handoff`` callback on
  the pump thread — the place to hand prefix-cache-warm state to a
  successor via the router's existing ``retire_replica`` spill-format
  machinery.

Metrics (PR-13 registry): ``dstpu_http_requests_total{code}``,
``dstpu_http_active_streams``, ``dstpu_http_stream_abort_total{reason}``
and socket-level ``dstpu_http_ttft_ms`` / ``dstpu_http_tpot_ms``
histograms; the same series names are recordable SLO objectives (fed
to the router's ``SLOSet`` on the pump thread).  Tracing: ``cat="http"``
accept/close instants and parse/admit/stream/flush spans; hard server
failures dump the flight ring with the active-connection table.
"""
from __future__ import annotations

import asyncio
import itertools
import queue
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving import protocol as proto
from deepspeed_tpu.telemetry import flight, trace
from deepspeed_tpu.telemetry import metrics as _metrics_mod

__all__ = ["FrontDoorServer"]


class _Stream:
    """Per-request bridge: the asyncio side awaits ``q``; the pump
    thread posts into it via ``call_soon_threadsafe``."""

    __slots__ = ("cid", "q", "rid")

    def __init__(self, cid: int, q: "asyncio.Queue") -> None:
        self.cid = cid
        self.q = q
        self.rid: Optional[int] = None


class FrontDoorServer:
    """Serve a router over HTTP/1.1 + SSE.

    Parameters
    ----------
    router:
        a :class:`~.router.Router`; the server flips its
        ``collect_events`` on and becomes the sole ``poll_events``
        consumer.  The caller keeps ownership (replicas are not closed
        on drain).
    host / port:
        bind address; ``port=0`` picks a free port (read it back from
        ``server.port`` after ``start()``).
    handoff:
        optional ``callable(router) -> Any`` run on the PUMP thread
        after drain completes (in-flight streams finished, admission
        closed) — e.g. ``lambda r: r.retire_replica("r0",
        target="r2")`` to hand prefix-cache-warm state to a successor.
        Its return value lands in ``handoff_result``.
    retry_after_s:
        ``Retry-After`` header value for 503 (draining) and 429
        responses.
    """

    def __init__(self, router: Any, host: str = "127.0.0.1",
                 port: int = 0, *, registry: Any = "auto",
                 retry_after_s: float = 2.0,
                 handoff: Optional[Callable[[Any], Any]] = None,
                 max_body: int = 1 << 20,
                 poll_interval_s: float = 0.005,
                 clock: Callable[[], float] = time.perf_counter) -> None:
        self.router = router
        router.collect_events = True
        self.host = host
        self.port = int(port)
        self.clock = clock
        self.retry_after_s = max(float(retry_after_s), 1.0)
        self.max_body = int(max_body)
        self._handoff = handoff
        self.handoff_result: Any = None
        self._poll = float(poll_interval_s)
        self._registry = registry

        self._cmds: "queue.Queue" = queue.Queue()
        self._streams: Dict[int, _Stream] = {}     # pump thread only
        self._cid = itertools.count()
        self._conns: Dict[int, Dict[str, Any]] = {}
        self._conns_lock = threading.Lock()
        self._handlers = 0                         # asyncio thread only

        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._pump_thread: Optional[threading.Thread] = None
        self._draining = False
        self._started = threading.Event()
        self._start_err: Optional[BaseException] = None
        self._aio_idle = threading.Event()
        self._drained = threading.Event()

    # -- metrics (resolved per observation: survives registry reset) -----

    def _reg(self):
        reg = self._registry
        if reg == "auto":
            reg = _metrics_mod.metrics
        return reg if (reg and getattr(reg, "enabled", False)) else None

    def _count_response(self, code: int) -> None:
        reg = self._reg()
        if reg and code:
            reg.counter("dstpu_http_requests_total",
                        "HTTP responses by status code",
                        labels=("code",)).labels(code=str(code)).inc()

    def _active_streams(self, delta: int) -> None:
        reg = self._reg()
        if reg:
            reg.gauge("dstpu_http_active_streams",
                      "SSE streams currently open").add(delta)

    def _count_abort(self, reason: str) -> None:
        reg = self._reg()
        if reg:
            reg.counter("dstpu_http_stream_abort_total",
                        "streams aborted before completion",
                        labels=("reason",)).labels(reason=reason).inc()

    def _observe_latency(self, name: str, value_ms: float) -> None:
        reg = self._reg()
        if reg:
            reg.histogram(f"dstpu_http_{name}",
                          f"socket-level {name} (ms)",
                          buckets=_metrics_mod.MS_BUCKETS
                          ).observe(value_ms)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "FrontDoorServer":
        """Bind, listen, and start the loop + pump threads; returns
        once the socket is accepting (``self.port`` is then real)."""
        if self._loop_thread is not None:
            raise RuntimeError("server already started")
        self._loop_thread = threading.Thread(
            target=self._run_loop, name="dstpu-frontdoor-aio",
            daemon=True)
        self._loop_thread.start()
        self._started.wait()
        if self._start_err is not None:
            raise RuntimeError(
                f"front door failed to bind {self.host}:{self.port}"
            ) from self._start_err
        self._pump_thread = threading.Thread(
            target=self._pump_loop, name="dstpu-frontdoor-pump",
            daemon=True)
        self._pump_thread.start()
        return self

    def install_signal_handlers(self,
                                signums: Tuple[int, ...] = (
                                    signal.SIGTERM,)) -> None:
        """SIGTERM -> ``begin_drain`` (rolling-restart contract).  Must
        run on the main thread (CPython's signal rule); the handler
        only flips flags and enqueues — safe at any interrupt point."""
        for s in signums:
            signal.signal(s, lambda _sig, _frm: self.begin_drain())

    def begin_drain(self) -> None:
        """Stop admitting (new requests get 503 + Retry-After), finish
        in-flight streams, then hand off + shut down.  Idempotent."""
        if self._draining:
            return
        self._draining = True
        self._cmds.put(("drain",))

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def serve_forever(self) -> None:
        """Blocking convenience for CLI use: start (if needed), then
        sleep until drained (SIGTERM or ``begin_drain``)."""
        if self._loop_thread is None:
            self.start()
        while not self._drained.wait(0.2):
            pass
        self.close()

    def close(self, timeout: float = 30.0) -> None:
        """Graceful teardown: drain, wait, join both threads.  The
        router and its replicas stay open (caller owns them)."""
        self.begin_drain()
        self._drained.wait(timeout)
        self._stop_loop()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=10.0)

    @property
    def draining(self) -> bool:
        return self._draining

    def connection_table(self) -> List[Dict[str, Any]]:
        """Active-connection snapshot (rides flight dumps on server
        hard failures)."""
        now = self.clock()
        with self._conns_lock:
            rows = [dict(c) for c in self._conns.values()]
        for c in rows:
            c["age_s"] = round(now - c.pop("t_accept"), 3)
        return rows

    # -- pump thread: the only router caller -----------------------------

    def _pump_loop(self) -> None:
        r = self.router
        try:
            while True:
                busy = self._drain_cmds()
                if r.outstanding or r.queued:
                    r.pump()
                    r.join()
                    busy = True
                for ev in r.poll_events():
                    self._on_router_event(ev)
                if (self._draining and not r.outstanding
                        and self._cmds.empty() and not self._streams):
                    break
                if not busy:
                    try:
                        self._do_cmd(self._cmds.get(timeout=self._poll))
                    except queue.Empty:
                        pass
        except BaseException as e:
            flight.dump_on_fault(
                "frontdoor_pump_failure", e,
                extra={"active_connections": self.connection_table()})
            for st in list(self._streams.values()):
                self._post(st, ("error", "server_error"))
            self._streams.clear()
            self._drained.set()
            self._stop_loop()
            raise
        # graceful exit: wait for in-flight handlers to flush their
        # final SSE bytes before the listener goes away.  Keep draining
        # commands meanwhile — a handler that raced the drain flag gets
        # its DrainingRejection folded back instead of hanging on an
        # unserviced submit
        deadline = self.clock() + 60.0
        while True:
            if self._loop is not None:
                self._loop.call_soon_threadsafe(self._check_aio_idle)
            if (self._aio_idle.wait(timeout=0.02)
                    or self.clock() >= deadline):
                break
            self._drain_cmds()
        if self._handoff is not None:
            try:
                self.handoff_result = self._handoff(r)
            except Exception as e:
                flight.dump_on_fault(
                    "frontdoor_handoff_failure", e,
                    extra={"active_connections":
                           self.connection_table()})
        trace.event("http_drained", cat="http",
                    finished=int(r.stats_counters.get("finished", 0)))
        self._drained.set()
        self._stop_loop()

    def _drain_cmds(self) -> bool:
        busy = False
        while True:
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                return busy
            self._do_cmd(cmd)
            busy = True

    def _do_cmd(self, cmd: Tuple) -> None:
        kind = cmd[0]
        r = self.router
        if kind == "submit":
            greq, st = cmd[1], cmd[2]
            try:
                rid = r.submit(np.asarray(greq.prompt, np.int32),
                               priority=greq.priority,
                               deadline_ms=greq.deadline_ms,
                               **greq.engine_kwargs())
            except Exception as e:
                self._post(st, ("rejected", e))
                return
            st.rid = rid
            self._streams[rid] = st
            self._post(st, ("accepted", rid))
        elif kind == "cancel":
            rid, reason = cmd[1], cmd[2]
            st = self._streams.pop(rid, None)
            if st is not None:
                r.cancel(rid)
                trace.event("http_cancel", cat="http", conn=st.cid,
                            rid=rid, reason=reason)
        elif kind == "drain":
            r.begin_drain()
            if self._loop is not None:
                self._loop.call_soon_threadsafe(self._check_aio_idle)
        elif kind == "observe":
            # SLOSet is single-threaded; socket latencies recorded here
            _, ttft_ms, tpot_ms = cmd
            if r.slo is not None:
                if ttft_ms is not None:
                    r.slo.record("http_ttft_ms", ttft_ms)
                if tpot_ms is not None:
                    r.slo.record("http_tpot_ms", tpot_ms)

    def _on_router_event(self, ev: Tuple[str, int, Any]) -> None:
        kind, rid, payload = ev
        st = self._streams.get(rid)
        if st is None:
            return
        if kind == "tokens":
            self._post(st, ("tokens", payload))
        elif kind == "finish":
            del self._streams[rid]
            self._post(st, ("finish", payload))
        elif kind == "deadline_expired":
            del self._streams[rid]
            self._post(st, ("expired", None))
        elif kind == "replica_death":
            # sampled request whose replica died mid-stream: replaying
            # on a survivor would contradict already-emitted tokens, so
            # the router failed it — surface a typed SSE error
            del self._streams[rid]
            self._post(st, ("replica_death", None))
        elif kind == "cancelled":
            # cancels originate from the handler; it stopped reading
            self._streams.pop(rid, None)

    def _post(self, st: _Stream, item: Tuple) -> None:
        loop = self._loop
        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(st.q.put_nowait, item)
            except RuntimeError:
                pass              # loop shut down mid-post

    # -- asyncio loop thread ---------------------------------------------

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        try:
            self._server = loop.run_until_complete(asyncio.start_server(
                self._handle, self.host, self.port))
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as e:
            self._start_err = e
            self._started.set()
            loop.close()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            self._server.close()
            loop.run_until_complete(self._server.wait_closed())
            pending = asyncio.all_tasks(loop)
            for t in pending:
                t.cancel()
            if pending:
                loop.run_until_complete(asyncio.gather(
                    *pending, return_exceptions=True))
            loop.close()

    def _stop_loop(self) -> None:
        loop = self._loop
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(loop.stop)

    def _check_aio_idle(self) -> None:
        if self._draining and self._handlers == 0:
            self._aio_idle.set()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        cid = next(self._cid)
        self._handlers += 1
        conn = {"conn": cid,
                "peer": str(writer.get_extra_info("peername")),
                "path": "", "rid": None, "state": "accept",
                "tokens_streamed": 0, "t_accept": self.clock()}
        with self._conns_lock:
            self._conns[cid] = conn
        if trace.enabled:
            trace.event("http_accept", cat="http", conn=cid)
        code = 0
        try:
            code = await self._route(reader, writer, conn)
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass                  # client went away; nothing to answer
        except asyncio.CancelledError:
            raise                 # loop shutdown
        except Exception as e:
            flight.dump_on_fault(
                "http_handler_failure", e,
                extra={"active_connections": self.connection_table()})
            code = 500
            try:
                writer.write(proto.json_response(
                    500, {"error": "internal_error"}))
                await writer.drain()
            except Exception:
                pass
        finally:
            self._count_response(code)
            if trace.enabled:
                trace.event("http_close", cat="http", conn=cid,
                            code=int(code))
            try:
                writer.close()
            except Exception:
                pass
            with self._conns_lock:
                self._conns.pop(cid, None)
            self._handlers -= 1
            self._check_aio_idle()

    async def _route(self, reader, writer, conn) -> int:
        cid = conn["conn"]
        t0 = self.clock()
        try:
            hreq = await proto.read_request(reader, self.max_body)
        except proto.ProtocolError as e:
            writer.write(proto.json_response(
                e.status, {"error": "protocol_error",
                           "detail": str(e)}))
            await writer.drain()
            return e.status
        finally:
            if trace.enabled:
                trace.add_complete("http_parse", t0, self.clock() - t0,
                                   cat="http", conn=cid)
        if hreq is None:
            return 0              # clean EOF before any bytes
        conn["path"] = hreq.path
        if hreq.path == "/healthz":
            if self._draining:
                writer.write(proto.json_response(
                    503, {"status": "draining"},
                    extra_headers=(("Retry-After",
                                    str(int(self.retry_after_s))),)))
                await writer.drain()
                return 503
            writer.write(proto.json_response(
                200, {"status": "ok",
                      "replicas": len(self.router.handles)}))
            await writer.drain()
            return 200
        if hreq.path == "/metrics":
            reg = self._reg()
            body = (reg.export_text() if reg else "").encode("utf-8")
            writer.write(proto.response(
                200, body, content_type="text/plain; version=0.0.4"))
            await writer.drain()
            return 200
        if hreq.path == "/v1/generate":
            if hreq.method != "POST":
                writer.write(proto.json_response(
                    405, {"error": "method_not_allowed"}))
                await writer.drain()
                return 405
            return await self._generate(hreq, reader, writer, conn)
        writer.write(proto.json_response(404, {"error": "not_found"}))
        await writer.drain()
        return 404

    # -- /v1/generate ----------------------------------------------------

    async def _generate(self, hreq, reader, writer, conn) -> int:
        cid = conn["conn"]
        retry = (("Retry-After", str(int(self.retry_after_s))),)
        if self._draining:
            writer.write(proto.json_response(
                503, {"error": "DrainingRejection",
                      "detail": "server is draining"},
                extra_headers=retry))
            await writer.drain()
            return 503
        try:
            greq = proto.GenerateRequest.from_body(hreq.body)
        except proto.ProtocolError as e:
            writer.write(proto.json_response(
                e.status, {"error": "bad_request", "detail": str(e)}))
            await writer.drain()
            return e.status
        st = _Stream(cid, asyncio.Queue())
        t_admit = self.clock()
        conn["state"] = "admit"
        self._cmds.put(("submit", greq, st))
        kind, payload = await st.q.get()
        if trace.enabled:
            trace.add_complete("http_admit", t_admit,
                               self.clock() - t_admit, cat="http",
                               conn=cid, accepted=kind == "accepted")
        if kind == "rejected":
            code, etype = proto.rejection_status(payload)
            if code == 500:
                flight.dump_on_fault(
                    "http_submit_failure", payload,
                    extra={"active_connections":
                           self.connection_table()})
            writer.write(proto.json_response(
                code, {"error": etype, "detail": str(payload)},
                extra_headers=retry if code in (429, 503) else ()))
            await writer.drain()
            return code
        rid = payload
        conn["rid"] = rid
        conn["state"] = "stream"
        if greq.stream:
            return await self._stream_sse(
                st, greq, reader, writer, conn, t_admit)
        return await self._respond_buffered(
            st, reader, writer, conn, t_admit)

    async def _watch_disconnect(self, reader) -> None:
        """Resolves when the peer goes away (EOF or reset).  With the
        request body fully consumed, any further bytes are junk — only
        the connection state matters."""
        try:
            while True:
                b = await reader.read(65536)
                if not b:
                    return
        except Exception:
            return

    async def _stream_sse(self, st, greq, reader, writer, conn,
                          t_admit) -> int:
        cid, rid = conn["conn"], st.rid
        self._active_streams(+1)
        writer.write(proto.sse_preamble())
        await writer.drain()
        t_stream0 = self.clock()
        t_first: Optional[float] = None
        t_last: Optional[float] = None
        ntok = 0
        abort: Optional[str] = None
        final: Optional[List[int]] = None
        watcher = asyncio.ensure_future(self._watch_disconnect(reader))
        try:
            while True:
                getter = asyncio.ensure_future(st.q.get())
                done, _ = await asyncio.wait(
                    {getter, watcher},
                    return_when=asyncio.FIRST_COMPLETED)
                if getter not in done:
                    getter.cancel()
                    abort = "client_disconnect"
                    break
                kind, payload = getter.result()
                now = self.clock()
                if kind == "tokens":
                    toks = [int(t) for t in payload]
                    if t_first is None:
                        t_first = now
                        self._observe_latency(
                            "ttft_ms", (now - t_admit) * 1e3)
                    t_last = now
                    ntok += len(toks)
                    conn["tokens_streamed"] = ntok
                    try:
                        d = faults.hook("http.flush", conn=cid, rid=rid)
                        if d is not None and d[0] in ("hang", "slow"):
                            await asyncio.sleep(float(d[1]))
                        writer.write(proto.sse_event(
                            "tokens", {"tokens": toks}))
                        await writer.drain()
                    except (ConnectionResetError, BrokenPipeError,
                            OSError):
                        abort = "write_error"
                        break
                elif kind == "finish":
                    final = [int(t) for t in payload]
                    break
                elif kind == "expired":
                    abort = "deadline_expired"
                    break
                elif kind == "replica_death":
                    abort = "replica_death"
                    break
                else:             # ("error", reason) — pump failure
                    abort = str(payload)
                    break
        finally:
            watcher.cancel()
            self._active_streams(-1)
        if final is not None:
            try:
                writer.write(proto.sse_event(
                    "done", {"tokens": final, "streamed": ntok}))
                if trace.enabled:
                    tf = self.clock()
                    await writer.drain()
                    trace.add_complete("http_flush", tf,
                                       self.clock() - tf, cat="http",
                                       conn=cid)
                else:
                    await writer.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                abort = "write_error"
        if abort is not None:
            self._count_abort(abort)
            if abort in ("client_disconnect", "write_error"):
                # the router + engine reclaim the slot, pool pages and
                # any tiered spill state mid-decode
                self._cmds.put(("cancel", rid, abort))
            else:
                try:
                    writer.write(proto.sse_event("error",
                                                 {"error": abort}))
                    await writer.drain()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass
        ttft_ms = ((t_first - t_admit) * 1e3
                   if t_first is not None else None)
        tpot_ms = None
        if (ntok >= 2 and t_first is not None and t_last is not None
                and t_last > t_first):
            tpot_ms = (t_last - t_first) * 1e3 / (ntok - 1)
            self._observe_latency("tpot_ms", tpot_ms)
        if ttft_ms is not None and self.router.slo is not None:
            self._cmds.put(("observe", ttft_ms, tpot_ms))
        if trace.enabled:
            trace.add_complete("http_stream", t_stream0,
                               self.clock() - t_stream0, cat="http",
                               conn=cid, tokens=ntok,
                               abort=abort or "")
        return 200

    async def _respond_buffered(self, st, reader, writer, conn,
                                t_admit) -> int:
        """``stream: false`` — buffer the whole generation, answer one
        JSON body (deadline expiry still gets its typed 429; a
        disconnect still cancels)."""
        rid = st.rid
        watcher = asyncio.ensure_future(self._watch_disconnect(reader))
        final: Optional[List[int]] = None
        abort: Optional[str] = None
        ntok = 0
        try:
            while True:
                getter = asyncio.ensure_future(st.q.get())
                done, _ = await asyncio.wait(
                    {getter, watcher},
                    return_when=asyncio.FIRST_COMPLETED)
                if getter not in done:
                    getter.cancel()
                    abort = "client_disconnect"
                    break
                kind, payload = getter.result()
                if kind == "tokens":
                    ntok += len(payload)
                elif kind == "finish":
                    final = [int(t) for t in payload]
                    break
                elif kind == "expired":
                    abort = "deadline_expired"
                    break
                elif kind == "replica_death":
                    abort = "replica_death"
                    break
                else:
                    abort = str(payload)
                    break
        finally:
            watcher.cancel()
        if abort is not None:
            self._count_abort(abort)
            if abort == "client_disconnect":
                self._cmds.put(("cancel", rid, abort))
                return 0
            code = 429 if abort == "deadline_expired" else 500
            writer.write(proto.json_response(
                code, {"error": ("DeadlineRejection" if code == 429
                                 else abort if abort == "replica_death"
                                 else "internal_error"),
                       "detail": abort}))
            await writer.drain()
            return code
        self._observe_latency("ttft_ms",
                              (self.clock() - t_admit) * 1e3)
        writer.write(proto.json_response(200, {"tokens": final}))
        await writer.drain()
        return 200
