"""Asyncio SSE client + load generator for the network front door.

Measures what the server cannot: TTFT and TPOT **at the socket** —
wall-clock from the last request byte written to each SSE event
arriving, including HTTP parse, queueing, and kernel socket buffers.
The in-process bench numbers (``detail.frontdoor``'s control row) are
the same quantities without the network front door in the path; the
delta IS the front door's overhead.

Two load shapes:

- **closed-loop**: ``concurrency`` workers, each holding exactly one
  open stream, back-to-back for ``requests`` total — measures capacity
  at a fixed stream count.
- **open-loop Poisson**: arrivals at ``rate`` req/s from a seeded
  exponential inter-arrival clock, independent of completions — the
  honest latency-under-load shape (a closed loop self-throttles when
  the server slows down; an open loop keeps arriving).

``abort_after_events`` hard-aborts the TCP transport mid-stream after
N SSE events — the client half of disconnect-cancellation testing
(the server must reclaim the slot, pool pages and tiered spill state).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import random
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.serving import protocol as proto

__all__ = ["sse_generate", "LoadGenerator", "bimodal_payload_fn",
           "percentile"]


def percentile(xs: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy needed client-side)."""
    if not xs:
        return float("nan")
    s = sorted(xs)
    i = min(int(q / 100.0 * len(s)), len(s) - 1)
    return s[i]


def bimodal_payload_fn(requests: int, *, short_len: int = 8,
                       long_len: int = 64, long_frac: float = 0.25,
                       max_new_tokens: int = 16, vocab: int = 64,
                       seed: int = 0,
                       deadline_ms: Optional[float] = None):
    """Seeded bimodal long-prefill / short-chat workload mix.

    Each request is independently a **long** prefill with probability
    ``long_frac`` (prompt length ``long_len``) or a **short** chat turn
    (``short_len``).  The split and every prompt token come from one
    ``random.Random(seed)`` stream, so the same seed reproduces the
    same workload byte-for-byte — required for bit-parity comparisons
    between serving topologies (fused vs. disaggregated) under the
    *same* traffic.

    Returns ``(payload_fn, kinds)``: the ``payload_fn`` to hand to
    :class:`LoadGenerator` and a per-request ``"long"``/``"short"``
    label list for phase-split latency reporting.
    """
    rng = random.Random(seed)
    kinds = ["long" if rng.random() < float(long_frac) else "short"
             for _ in range(int(requests))]
    prompts = [[rng.randrange(1, int(vocab)) for _ in
                range(long_len if k == "long" else short_len)]
               for k in kinds]

    def payload(i: int) -> Dict[str, Any]:
        p: Dict[str, Any] = {"prompt": prompts[i],
                             "max_new_tokens": int(max_new_tokens)}
        if deadline_ms is not None:
            p["deadline_ms"] = float(deadline_ms)
        return p

    return payload, kinds


async def sse_generate(host: str, port: int, payload: Dict[str, Any],
                       clock: Callable[[], float] = time.perf_counter,
                       abort_after_events: Optional[int] = None
                       ) -> Dict[str, Any]:
    """One ``POST /v1/generate`` over a raw socket; returns::

        {"status": int, "error": str|None, "tokens": [streamed...],
         "final": [prompt+generated]|None, "events": int,
         "ttft_s": float|None, "tpot_s": float|None, "total_s": float}

    ``ttft_s`` is last-request-byte -> first ``tokens`` event;
    ``tpot_s`` is the mean gap between streamed tokens after the
    first.  ``abort_after_events=N`` kills the TCP transport after N
    SSE events (disconnect-cancellation testing); the result then has
    ``error="client_abort"``.
    """
    body = json.dumps(payload).encode("utf-8")
    reader, writer = await asyncio.open_connection(host, port)
    out: Dict[str, Any] = {"status": 0, "error": None, "tokens": [],
                           "final": None, "events": 0, "ttft_s": None,
                           "tpot_s": None, "total_s": 0.0}
    t0 = clock()
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    try:
        writer.write(
            b"POST /v1/generate HTTP/1.1\r\n"
            b"Host: " + host.encode("latin-1") + b"\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n"
            b"Connection: close\r\n\r\n" + body)
        await writer.drain()
        t0 = clock()              # request fully written: the TTFT zero
        head = await reader.readuntil(b"\r\n\r\n")
        lines = head.decode("latin-1").split("\r\n")
        out["status"] = int(lines[0].split(" ")[1])
        headers = {}
        for ln in lines[1:]:
            if ":" in ln:
                k, _, v = ln.partition(":")
                headers[k.strip().lower()] = v.strip()
        if (out["status"] != 200
                or "text/event-stream" not in headers.get(
                    "content-type", "")):
            raw = await reader.read(int(headers.get("content-length",
                                                    65536)) or 65536)
            try:
                err = json.loads(raw.decode("utf-8"))
                out["error"] = err.get("error", "http_error")
                out["detail"] = err.get("detail", "")
                if out["status"] == 200:   # buffered (stream=false) reply
                    out["error"] = None
                    out["final"] = err.get("tokens")
            except (json.JSONDecodeError, UnicodeDecodeError):
                out["error"] = "http_error"
            return out
        parser = proto.SSEParser()
        while True:
            chunk = await reader.read(65536)
            if not chunk:
                if out["final"] is None and out["error"] is None:
                    out["error"] = "truncated_stream"
                return out
            for event, data in parser.feed(chunk):
                out["events"] += 1
                now = clock()
                if event == "tokens":
                    toks = json.loads(data)["tokens"]
                    if t_first is None:
                        t_first = now
                    t_last = now
                    out["tokens"].extend(int(t) for t in toks)
                elif event == "done":
                    obj = json.loads(data)
                    out["final"] = [int(t) for t in obj["tokens"]]
                    return out
                elif event == "error":
                    out["error"] = json.loads(data).get("error",
                                                        "error")
                    return out
                if (abort_after_events is not None
                        and out["events"] >= abort_after_events):
                    out["error"] = "client_abort"
                    writer.transport.abort()   # RST, not FIN: the
                    return out                 # rudest disconnect
    finally:
        out["total_s"] = clock() - t0
        if t_first is not None:
            out["ttft_s"] = t_first - t0
            n = len(out["tokens"])
            if n >= 2 and t_last is not None and t_last > t_first:
                out["tpot_s"] = (t_last - t_first) / (n - 1)
        try:
            writer.close()
        except Exception:
            pass


class LoadGenerator:
    """Drive a front door with N concurrent SSE streams and collect
    socket-level latency percentiles.

    Parameters
    ----------
    host / port:
        the front door.
    payload_fn:
        ``callable(i) -> dict`` building request ``i``'s JSON body
        (vary prompts for prefix-cache realism; keep them fixed for
        bit-parity checks).
    concurrency:
        closed-loop worker count == max open streams.
    rate:
        open-loop Poisson arrival rate (req/s); ``None`` (default)
        selects the closed loop.  Open-loop still caps open streams at
        ``concurrency`` (an arrival past the cap waits, and the wait
        shows up in TTFT — exactly what an overloaded open loop should
        report).
    seed:
        inter-arrival RNG seed (reproducible arrival process).
    kinds:
        optional per-request workload label (e.g. the ``"long"`` /
        ``"short"`` list from :func:`bimodal_payload_fn`); when given,
        the summary reports TTFT percentiles per label so a mixed
        workload's long-prefill tail doesn't hide inside the aggregate.
    """

    def __init__(self, host: str, port: int,
                 payload_fn: Callable[[int], Dict[str, Any]],
                 requests: int = 64, concurrency: int = 8,
                 rate: Optional[float] = None, seed: int = 0,
                 clock: Callable[[], float] = time.perf_counter,
                 kinds: Optional[List[str]] = None) -> None:
        self.host, self.port = host, int(port)
        self.payload_fn = payload_fn
        self.requests = int(requests)
        self.concurrency = max(int(concurrency), 1)
        self.rate = rate
        self.seed = int(seed)
        self.clock = clock
        self.kinds = list(kinds) if kinds is not None else None
        self.results: List[Dict[str, Any]] = []

    async def _one(self, i: int, sem: asyncio.Semaphore) -> None:
        async with sem:
            try:
                res = await sse_generate(self.host, self.port,
                                         self.payload_fn(i),
                                         clock=self.clock)
            except (OSError, asyncio.IncompleteReadError) as e:
                res = {"status": 0, "error": f"conn: {e}", "tokens": [],
                       "final": None, "events": 0, "ttft_s": None,
                       "tpot_s": None, "total_s": 0.0}
            res["i"] = i
            self.results.append(res)

    async def _run_async(self) -> None:
        sem = asyncio.Semaphore(self.concurrency)
        if self.rate is None:
            tasks = [asyncio.ensure_future(self._one(i, sem))
                     for i in range(self.requests)]
        else:
            rng = random.Random(self.seed)
            tasks = []
            for i in range(self.requests):
                tasks.append(asyncio.ensure_future(self._one(i, sem)))
                await asyncio.sleep(rng.expovariate(self.rate))
        await asyncio.gather(*tasks)

    def run(self) -> Dict[str, Any]:
        self.results = []
        t0 = self.clock()
        asyncio.run(self._run_async())
        wall = self.clock() - t0
        return self.summary(wall)

    def summary(self, wall_s: float) -> Dict[str, Any]:
        ok = [r for r in self.results if r["final"] is not None]
        errs: Dict[str, int] = {}
        for r in self.results:
            if r["error"]:
                errs[r["error"]] = errs.get(r["error"], 0) + 1
        ttft = [r["ttft_s"] * 1e3 for r in ok if r["ttft_s"] is not None]
        tpot = [r["tpot_s"] * 1e3 for r in ok if r["tpot_s"] is not None]
        by_kind: Dict[str, Any] = {}
        if self.kinds is not None:
            for kind in sorted(set(self.kinds)):
                ks = [r["ttft_s"] * 1e3 for r in ok
                      if r["ttft_s"] is not None
                      and r["i"] < len(self.kinds)
                      and self.kinds[r["i"]] == kind]
                by_kind[kind] = {
                    "requests": sum(1 for k in self.kinds if k == kind),
                    "ttft_ms_p50": round(percentile(ks, 50), 3),
                    "ttft_ms_p99": round(percentile(ks, 99), 3),
                }
        return {
            "mode": ("closed" if self.rate is None
                     else f"poisson@{self.rate:g}/s"),
            "requests": len(self.results), "completed": len(ok),
            "errors": errs, "concurrency": self.concurrency,
            "wall_s": round(wall_s, 3),
            "requests_per_s": round(len(ok) / wall_s, 3) if wall_s else 0.0,
            "tokens_streamed": sum(len(r["tokens"]) for r in ok),
            "ttft_ms_p50": round(percentile(ttft, 50), 3),
            "ttft_ms_p90": round(percentile(ttft, 90), 3),
            "ttft_ms_p99": round(percentile(ttft, 99), 3),
            "tpot_ms_p50": round(percentile(tpot, 50), 3),
            "tpot_ms_p99": round(percentile(tpot, 99), 3),
            **({"by_kind": by_kind} if by_kind else {}),
        }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="SSE load generator for the dstpu front door")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rate", type=float, default=None,
                    help="open-loop Poisson arrival rate (req/s); "
                         "default closed-loop")
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--long-frac", type=float, default=0.0,
                    help="bimodal mix: fraction of requests that are "
                         "long prefills (0 disables the mix)")
    ap.add_argument("--long-prompt-len", type=int, default=64,
                    help="prompt length of the long-prefill mode")
    args = ap.parse_args(argv)

    kinds: Optional[List[str]] = None
    if args.long_frac > 0.0:
        payload, kinds = bimodal_payload_fn(
            args.requests, short_len=args.prompt_len,
            long_len=args.long_prompt_len, long_frac=args.long_frac,
            max_new_tokens=args.max_new_tokens, vocab=args.vocab,
            seed=args.seed, deadline_ms=args.deadline_ms)
    else:
        rng = random.Random(args.seed)
        prompts = [[rng.randrange(1, args.vocab) for _ in
                    range(args.prompt_len)] for _ in range(args.requests)]

        def payload(i: int) -> Dict[str, Any]:
            p: Dict[str, Any] = {"prompt": prompts[i],
                                 "max_new_tokens": args.max_new_tokens}
            if args.deadline_ms is not None:
                p["deadline_ms"] = args.deadline_ms
            return p

    gen = LoadGenerator(args.host, args.port, payload,
                        requests=args.requests,
                        concurrency=args.concurrency, rate=args.rate,
                        seed=args.seed, kinds=kinds)
    summary = gen.run()
    print(json.dumps(summary, indent=2, sort_keys=True))
    return 0 if summary["completed"] == summary["requests"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
