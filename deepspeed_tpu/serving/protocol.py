"""Wire protocol for the network front door: stdlib HTTP/1.1 + SSE.

No dependencies beyond asyncio's stream API — the server and client
both speak through these helpers, so the SSE framing and the request
schema are defined exactly once.

API schema (``POST /v1/generate``, JSON body)::

    {"prompt": [1, 17, 3, ...],        # required, non-empty int list
     "max_new_tokens": 64,             # optional
     "deadline_ms": 250.0,             # optional admission deadline
     "priority": 0,                    # optional router priority
     "stream": true,                   # SSE (default) vs buffered JSON
     "eos_token_id": 2,                # optional sampling params ...
     "do_sample": false, "temperature": 1.0, "top_k": 0, "top_p": 1.0}

SSE wire format (``Content-Type: text/event-stream``), one ``tokens``
event per engine HARVEST (the deferred-harvest pipeline's folding
grain — the honest streaming granularity), then exactly one terminal
event::

    event: tokens
    data: {"tokens": [437, 12]}

    event: done
    data: {"tokens": [<prompt + all generated>], "streamed": 12}

    event: error
    data: {"error": "deadline_expired"}
"""
from __future__ import annotations

import asyncio
import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ProtocolError", "HttpRequest", "GenerateRequest",
           "read_request", "sse_event", "sse_preamble", "SSEParser",
           "response", "json_response", "rejection_status", "REASONS"]

REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
           405: "Method Not Allowed", 413: "Payload Too Large",
           429: "Too Many Requests", 500: "Internal Server Error",
           503: "Service Unavailable"}


class ProtocolError(ValueError):
    """Malformed HTTP or request schema; carries the response code."""

    def __init__(self, msg: str, status: int = 400) -> None:
        super().__init__(msg)
        self.status = int(status)


@dataclasses.dataclass
class HttpRequest:
    method: str
    target: str
    path: str
    query: Dict[str, str]
    headers: Dict[str, str]              # keys lower-cased
    body: bytes


async def read_request(reader: asyncio.StreamReader,
                       max_body: int = 1 << 20
                       ) -> Optional[HttpRequest]:
    """Parse one HTTP/1.1 request off ``reader``.  Returns None on a
    clean EOF before any bytes (client connected and left); raises
    :class:`ProtocolError` on anything malformed."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise ProtocolError("truncated request head")
    except asyncio.LimitOverrunError:
        raise ProtocolError("request head too large", status=413)
    lines = head.decode("latin-1").split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
        raise ProtocolError(f"bad request line {lines[0]!r}")
    method, target = parts[0].upper(), parts[1]
    path, _, qs = target.partition("?")
    query: Dict[str, str] = {}
    for kv in qs.split("&"):
        if kv:
            k, _, v = kv.partition("=")
            query[k] = v
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, sep, v = line.partition(":")
        if not sep:
            raise ProtocolError(f"bad header line {line!r}")
        headers[k.strip().lower()] = v.strip()
    body = b""
    if "content-length" in headers:
        try:
            n = int(headers["content-length"])
        except ValueError:
            raise ProtocolError("bad Content-Length")
        if n < 0 or n > max_body:
            raise ProtocolError(f"body of {n} bytes exceeds the "
                                f"{max_body}-byte cap", status=413)
        try:
            body = await reader.readexactly(n)
        except asyncio.IncompleteReadError:
            raise ProtocolError("body shorter than Content-Length")
    return HttpRequest(method, target, path, query, headers, body)


_GEN_FIELDS = {"prompt", "max_new_tokens", "deadline_ms", "priority",
               "stream", "eos_token_id", "do_sample", "temperature",
               "top_k", "top_p"}


@dataclasses.dataclass
class GenerateRequest:
    """Validated ``/v1/generate`` body (the engine-facing half of the
    schema maps 1:1 onto ``put_request`` kwargs)."""

    prompt: List[int]
    max_new_tokens: int = 64
    deadline_ms: Optional[float] = None
    priority: int = 0
    stream: bool = True
    eos_token_id: Optional[int] = None
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0

    @classmethod
    def from_body(cls, body: bytes) -> "GenerateRequest":
        try:
            obj = json.loads(body.decode("utf-8") or "null")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ProtocolError(f"body is not valid JSON: {e}")
        if not isinstance(obj, dict):
            raise ProtocolError("body must be a JSON object")
        unknown = sorted(set(obj) - _GEN_FIELDS)
        if unknown:
            raise ProtocolError(f"unknown fields {unknown} "
                                f"(have {sorted(_GEN_FIELDS)})")
        prompt = obj.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           for t in prompt)):
            raise ProtocolError(
                "'prompt' must be a non-empty list of token ids (ints)")
        out = cls(prompt=[int(t) for t in prompt])
        for name, typ in (("max_new_tokens", int), ("priority", int),
                          ("top_k", int)):
            if name in obj:
                v = obj[name]
                if not isinstance(v, int) or isinstance(v, bool):
                    raise ProtocolError(f"'{name}' must be an int")
                setattr(out, name, typ(v))
        for name in ("deadline_ms", "temperature", "top_p"):
            if name in obj:
                v = obj[name]
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ProtocolError(f"'{name}' must be a number")
                setattr(out, name, float(v))
        for name in ("stream", "do_sample"):
            if name in obj:
                if not isinstance(obj[name], bool):
                    raise ProtocolError(f"'{name}' must be a bool")
                setattr(out, name, obj[name])
        if "eos_token_id" in obj and obj["eos_token_id"] is not None:
            v = obj["eos_token_id"]
            if not isinstance(v, int) or isinstance(v, bool):
                raise ProtocolError("'eos_token_id' must be an int")
            out.eos_token_id = int(v)
        if out.max_new_tokens < 1:
            raise ProtocolError("'max_new_tokens' must be >= 1")
        return out

    def engine_kwargs(self) -> Dict[str, Any]:
        """``put_request`` kwargs (deadline/priority/stream are router
        and transport concerns, never forwarded to the engine)."""
        kw: Dict[str, Any] = {"max_new_tokens": self.max_new_tokens}
        if self.eos_token_id is not None:
            kw["eos_token_id"] = self.eos_token_id
        if self.do_sample:
            kw.update(do_sample=True, temperature=self.temperature,
                      top_k=self.top_k, top_p=self.top_p)
        return kw


# -- SSE framing ---------------------------------------------------------

def sse_preamble() -> bytes:
    return (b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n\r\n")


def sse_event(event: str, data: Any) -> bytes:
    return (f"event: {event}\ndata: "
            f"{json.dumps(data, separators=(',', ':'))}\n\n"
            ).encode("utf-8")


class SSEParser:
    """Incremental SSE parser: ``feed(bytes)`` returns completed
    ``(event, data)`` pairs; partial events stay buffered."""

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> List[Tuple[str, str]]:
        self._buf += chunk
        out: List[Tuple[str, str]] = []
        while b"\n\n" in self._buf:
            block, self._buf = self._buf.split(b"\n\n", 1)
            event, data = "message", []
            for line in block.decode("utf-8").split("\n"):
                if line.startswith("event:"):
                    event = line[6:].strip()
                elif line.startswith("data:"):
                    data.append(line[5:].strip())
            if data:
                out.append((event, "\n".join(data)))
        return out


# -- responses -----------------------------------------------------------

def response(status: int, body: bytes = b"",
             content_type: str = "application/json",
             extra_headers: Tuple[Tuple[str, str], ...] = ()) -> bytes:
    head = [f"HTTP/1.1 {status} {REASONS.get(status, 'Unknown')}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}",
            "Connection: close"]
    head += [f"{k}: {v}" for k, v in extra_headers]
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


def json_response(status: int, obj: Any,
                  extra_headers: Tuple[Tuple[str, str], ...] = ()
                  ) -> bytes:
    return response(status, json.dumps(obj).encode("utf-8"),
                    extra_headers=extra_headers)


def rejection_status(exc: BaseException) -> Tuple[int, str]:
    """Map a typed router rejection to (HTTP status, error type).
    Unknown exceptions map to 500 — the caller dumps the flight ring
    for those."""
    from deepspeed_tpu.serving.router import (DeadlineRejection,
                                              DrainingRejection,
                                              NeverSchedulableRejection,
                                              QueueFullRejection,
                                              RouterRejection,
                                              ShedRejection)
    etype = type(exc).__name__
    if isinstance(exc, NeverSchedulableRejection):
        return 400, etype
    if isinstance(exc, (DeadlineRejection, QueueFullRejection,
                        ShedRejection)):
        return 429, etype
    if isinstance(exc, DrainingRejection):
        return 503, etype
    if isinstance(exc, RouterRejection):
        return 503, etype
    return 500, etype
