"""SLO objectives, rolling-window error-budget burn, tail sampling.

An objective is a string like ``"ttft_ms_p99 <= 150"``: the ``_pNN``
suffix names the percentile target (99% of samples must satisfy the
threshold), so the error budget is ``1 - 0.99 = 1%``.  ``SLOSet``
keeps a rolling time window of per-sample pass/fail and reports the
classic burn rate::

    burn_rate = observed_error_rate / error_budget

``burn_rate <= 1`` means the objective is healthy at steady state; 10
means the budget burns 10x too fast.  Clock is injectable (tests pin a
``ManualClock``), window arithmetic is plain deque-pruning — no
background thread.

``TailSampler`` is the promotion policy for tail-based trace sampling
(``DSTPU_TRACE_SAMPLE``): every finished request asks ``should_promote``
and the tracer copies that request's spans from the always-on staging
rings into the retained ring only when the request breached an SLO,
errored, or fell in a deterministic 1-in-N sample (seeded injectable
RNG — replayable in tests).
"""
from __future__ import annotations

import random
import re
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

__all__ = ["Objective", "parse_objective", "SLOSet", "TailSampler"]

_OBJ_RE = re.compile(
    r"^\s*([A-Za-z][A-Za-z0-9_]*?)_p(\d{1,2}(?:\.\d+)?)\s*(<=?)\s*"
    r"([0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*$")


class Objective:
    """One parsed objective: ``metric`` samples must be ``<= threshold``
    for at least ``target`` (fraction) of the window."""

    __slots__ = ("name", "metric", "target", "threshold")

    def __init__(self, name: str, metric: str, target: float,
                 threshold: float):
        if not (0.0 < target < 1.0):
            raise ValueError(f"{name}: target must be in (0, 1)")
        self.name = name
        self.metric = metric
        self.target = target
        self.threshold = threshold

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def __repr__(self):
        return (f"Objective({self.name!r}: {self.metric} p"
                f"{self.target * 100:g} <= {self.threshold:g})")


def parse_objective(spec: Union[str, Objective]) -> Objective:
    """``"ttft_ms_p99 <= 150"`` -> Objective(metric="ttft_ms",
    target=0.99, threshold=150).  ``p99.9`` sets target 0.999."""
    if isinstance(spec, Objective):
        return spec
    m = _OBJ_RE.match(str(spec))
    if not m:
        raise ValueError(
            f"bad SLO objective {spec!r} (want e.g. 'ttft_ms_p99 <= 150')")
    metric, pct, _op, thr = m.groups()
    target = float(pct) / 100.0
    name = f"{metric}_p{pct}"
    return Objective(name, metric, target, float(thr))


class SLOSet:
    """Rolling-window evaluation of a set of objectives.

    ``record(metric, value)`` feeds one sample to every objective on
    that metric and returns the names of objectives whose *sample*
    breached its threshold (the per-request signal the tail sampler
    promotes on).  ``evaluate()`` returns the window-level state:
    error rate, remaining budget, burn rate.
    """

    def __init__(self, objectives: Sequence[Union[str, Objective]],
                 window_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic):
        self.objectives: List[Objective] = [parse_objective(o)
                                            for o in objectives]
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO objectives: {names}")
        self.window_s = float(window_s)
        self.clock = clock
        # per-objective deque of (t, breached) samples inside the window
        self._samples: Dict[str, deque] = {o.name: deque()
                                           for o in self.objectives}
        self.total_samples = 0
        self.total_breaches = 0

    def _prune(self, dq: deque, now: float) -> None:
        horizon = now - self.window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def record(self, metric: str, value: float) -> List[str]:
        """Feed one sample; returns names of objectives this sample
        breached (empty when healthy or when no objective watches
        ``metric``)."""
        breached: List[str] = []
        now = self.clock()
        for o in self.objectives:
            if o.metric != metric:
                continue
            bad = value > o.threshold
            dq = self._samples[o.name]
            dq.append((now, bad))
            self._prune(dq, now)
            self.total_samples += 1
            if bad:
                self.total_breaches += 1
                breached.append(o.name)
        return breached

    def record_request(self, rec: Dict[str, Any]) -> List[str]:
        """Feed every numeric field of a per-request summary dict (the
        ``RequestLatencyTracker.on_finish`` return value); missing
        metrics are skipped."""
        breached: List[str] = []
        seen = set()
        for o in self.objectives:
            if o.metric in seen:        # record() covers every objective
                continue                # on the metric in one call
            seen.add(o.metric)
            v = rec.get(o.metric)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                breached.extend(self.record(o.metric, float(v)))
        return breached

    def evaluate(self) -> Dict[str, Dict[str, Any]]:
        """Window-level state per objective (all scalars — monitor and
        export_json embed it directly)."""
        now = self.clock()
        out: Dict[str, Dict[str, Any]] = {}
        for o in self.objectives:
            dq = self._samples[o.name]
            self._prune(dq, now)
            n = len(dq)
            bad = sum(1 for _t, b in dq if b)
            err = (bad / n) if n else 0.0
            budget = o.budget
            if budget > 0:
                burn = err / budget
            else:                      # pragma: no cover - target<1 enforced
                burn = float("inf") if bad else 0.0
            out[o.name] = {
                "metric": o.metric,
                "threshold": o.threshold,
                "target": o.target,
                "window_s": self.window_s,
                "samples": n,
                "breaches": bad,
                "error_rate": round(err, 6),
                "error_budget": round(budget, 6),
                "burn_rate": round(burn, 6),
                "ok": burn <= 1.0,
            }
        return out

    def flat_summary(self) -> Dict[str, Any]:
        """One level of scalars for ``serving_stages()["slo"]`` (the
        MonitorMaster flattening contract)."""
        out: Dict[str, Any] = {}
        for name, st in self.evaluate().items():
            out[f"{name}_burn_rate"] = st["burn_rate"]
            out[f"{name}_error_rate"] = st["error_rate"]
            out[f"{name}_samples"] = st["samples"]
            out[f"{name}_breaches"] = st["breaches"]
            out[f"{name}_ok"] = int(st["ok"])
        return out


class TailSampler:
    """Promotion policy: breach / error always promote; otherwise a
    deterministic 1-in-N draw on the injected RNG (``n <= 0`` disables
    the random arm — only breaches/errors are retained)."""

    def __init__(self, n: int = 0, seed: int = 0,
                 rng: Optional[random.Random] = None):
        self.n = int(n)
        self.rng = rng if rng is not None else random.Random(seed)
        self.decisions = 0
        self.promoted_breach = 0
        self.promoted_error = 0
        self.promoted_sample = 0
        self.dropped = 0

    def should_promote(self, breached: bool = False, errored: bool = False
                       ) -> Tuple[bool, str]:
        """Returns ``(promote, reason)``; reason in
        {"slo_breach", "error", "sample", ""}.  The RNG is consumed on
        *every* decision (even breach-promoted ones) so the 1-in-N
        stream stays aligned with the request stream — decision k for a
        given seed is the same regardless of interleaved breaches."""
        self.decisions += 1
        draw = self.rng.random() if self.n > 0 else 1.0
        if breached:
            self.promoted_breach += 1
            return True, "slo_breach"
        if errored:
            self.promoted_error += 1
            return True, "error"
        if self.n > 0 and draw * self.n < 1.0:
            self.promoted_sample += 1
            return True, "sample"
        self.dropped += 1
        return False, ""

    def counters(self) -> Dict[str, int]:
        return {
            "decisions": self.decisions,
            "promoted_breach": self.promoted_breach,
            "promoted_error": self.promoted_error,
            "promoted_sample": self.promoted_sample,
            "dropped": self.dropped,
        }
