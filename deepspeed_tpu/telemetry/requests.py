"""Per-request serving latency: TTFT / TPOT / queue-wait / spill-stall.

The ragged engine reports throughput (tok/s) but scale-out serving is
gated on per-request percentiles — a batch that sustains 20k tok/s can
still starve one request behind a spill storm.  The engine feeds this
tracker from its lifecycle hooks (submit → admit → token folds →
reap); ``summary()`` derives nearest-rank p50/p90/p99 over completed
requests and returns a FLAT dict (``MonitorMaster.write_serving_health``
flattens exactly one level of sub-dicts, so the shape must already be
scalar-valued).

Semantics under the pipelined host path: token timestamps are taken at
HARVEST (when the host folds device tokens back into request state) —
the honest host-visible latency, since the deferred-harvest pipeline
means the host cannot observe a token earlier than that.

- ``ttft``: first harvested token − submit (clamped at submit — a
  prefix-cache hit whose prefill is fully skipped can emit in the same
  scheduler tick it was admitted; the sample must be ≥ 0, never
  missing or negative)
- ``tpot``: (last − first token) / (tokens − 1), requests with ≥2 tokens
- ``queue_wait``: first admit − submit
- ``router_queue_wait``: first admit − router accept (only for
  requests that arrived through the scale-out router; its own series,
  so router queuing is never folded into TTFT)
- ``spill_stall``: accumulated restore-bracket seconds per request
- ``prefill``: admit → prefill-complete span, plus per-request counts
  of prefill tokens actually computed vs skipped via the prefix cache
  (a full prefix hit records a ~zero-length span, not a hole)

The tracker is always on (a few dict ops per request per harvest —
noise next to a device dispatch), independent of the tracer's enabled
flag, so the bench ragged row always carries ``request_latency``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.telemetry import metrics as _metrics_mod

__all__ = ["RequestLatencyTracker", "percentile"]

# Request-latency histograms (ms buckets).  Families are registered
# lazily on first observation so an import alone never mutates the
# registry; children are cached per tracker.
_HIST_SPECS = {
    "ttft_ms": "Time to first harvested token (ms)",
    "tpot_ms": "Per-token decode latency after the first token (ms)",
    "queue_wait_ms": "Submit to first admission (ms)",
    "router_queue_wait_ms":
        "Router accept to replica slot admission (ms)",
    "spill_stall_ms": "Restore-bracket stall attributed to the request (ms)",
    "prefill_ms": "Admission to prefill-complete (ms)",
    "handoff_stall_ms":
        "Prefill-replica export to decode-replica install (ms)",
}


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (ceil(q/100 * n)-th smallest) — hand
    computable for test fixtures; no interpolation."""
    if not values:
        return None
    vs = sorted(values)
    n = len(vs)
    rank = max(1, -(-int(q * n) // 100))          # ceil(q*n/100), >= 1
    return vs[min(rank, n) - 1]


class _Rec:
    __slots__ = ("uid", "submit_t", "admit_t", "first_token_t",
                 "last_token_t", "tokens", "spill_stall_s", "spills",
                 "finish_t", "prefill_end_t", "prefill_computed",
                 "prefill_cached", "errors", "router_accept_t",
                 "handoff_stall_s", "handoffs")

    def __init__(self, uid: Any, submit_t: float):
        self.uid = uid
        self.submit_t = submit_t
        self.router_accept_t: Optional[float] = None
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.tokens = 0
        self.spill_stall_s = 0.0
        self.spills = 0
        self.finish_t: Optional[float] = None
        self.prefill_end_t: Optional[float] = None
        self.prefill_computed = 0
        self.prefill_cached = 0
        self.errors = 0
        self.handoff_stall_s = 0.0
        self.handoffs = 0


class RequestLatencyTracker:
    """Lifecycle-fed latency percentiles, keyed by request uid."""

    PCTS = (50, 90, 99)

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 max_completed: int = 4096,
                 registry: Any = "auto", replica: str = ""):
        self.clock = clock
        # scale-out serving: one tracker per replica engine; the label
        # keeps their registry children apart (solo engines keep the
        # empty label value)
        self.replica = str(replica)
        # disaggregated serving: the replica's ROLE ("prefill"/"decode",
        # "" when fused) — folded into the histogram label so TTFT/TPOT
        # attribute to the right side of the split
        self.phase = ""
        self._live: Dict[Any, _Rec] = {}
        self._done: deque = deque(maxlen=max_completed)
        self.submitted = 0
        self.finished = 0
        self.cancelled = 0
        self.handed_off = 0
        # "auto": the process registry singleton (respects its enabled
        # flag); None/False: no metrics feed; else an injected registry.
        self._registry = registry
        self._hists: Dict[str, Any] = {}
        self._hist_fams: Dict[str, Any] = {}

    def set_replica(self, replica: str) -> None:
        """Re-label after construction (ReplicaSet assigns indices);
        drops cached children so future observations carry the label."""
        self.replica = str(replica)
        self._hists.clear()
        self._hist_fams.clear()

    def set_phase(self, phase: str) -> None:
        """Tag this tracker with the replica's serving role (``""`` /
        ``"prefill"`` / ``"decode"``); future observations land under a
        ``replica/phase`` label value so the two roles' TTFT/TPOT stay
        separate series."""
        self.phase = str(phase)
        self._hists.clear()
        self._hist_fams.clear()

    def _observe(self, name: str, value_ms: float) -> None:
        reg = self._registry
        if reg == "auto":
            reg = _metrics_mod.metrics
        if not reg or not reg.enabled:
            return
        h = self._hists.get(name)
        if h is None or self._hist_fams.get(name) is not reg.get(
                f"dstpu_request_{name}"):
            fam = reg.histogram(f"dstpu_request_{name}", _HIST_SPECS[name],
                                labels=("replica",),
                                buckets=_metrics_mod.MS_BUCKETS)
            self._hist_fams[name] = fam
            label = (f"{self.replica}/{self.phase}" if self.phase
                     else self.replica)
            h = fam.labels(replica=label)
            self._hists[name] = h
        h.observe(value_ms)

    # -- lifecycle hooks (called by the engine) --------------------------

    def on_submit(self, uid: Any) -> None:
        self._live[uid] = _Rec(uid, self.clock())
        self.submitted += 1

    def note_router_accept(self, uid: Any, accept_t: float) -> None:
        """Router-level accept timestamp (same clock as the tracker).
        The router calls this right after ``put_request`` returns the
        replica uid; ``router_queue_wait_ms`` (accept -> replica slot
        admission) then lands as its OWN series, so router queuing is
        never silently folded into TTFT."""
        r = self._live.get(uid)
        if r is not None and r.router_accept_t is None:
            r.router_accept_t = float(accept_t)

    def on_admit(self, uid: Any) -> None:
        r = self._live.get(uid)
        if r is not None and r.admit_t is None:   # first admit only —
            r.admit_t = self.clock()              # re-admits after evict
            pass                                  # are not queue wait

    def on_tokens(self, uid: Any, total_tokens: int) -> None:
        """``total_tokens`` is the request's cumulative generated count
        (idempotent — repeated calls with an unchanged count are no-ops)."""
        r = self._live.get(uid)
        if r is None or total_tokens <= r.tokens:
            return
        # clamp at submit so a fully-skipped prefill (prefix-cache hit
        # emitting in its admission tick) records TTFT >= 0 even under
        # a coarse injected clock
        now = max(self.clock(), r.submit_t)
        if r.first_token_t is None:
            r.first_token_t = now
        r.last_token_t = now
        r.tokens = total_tokens

    def on_prefill_done(self, uid: Any, computed_tokens: int,
                        cached_tokens: int = 0) -> None:
        """Prefill finished for ``uid``: ``computed_tokens`` went
        through the model, ``cached_tokens`` were skipped via the
        prefix cache.  First call wins (evict/re-prefill churn keeps
        the original span)."""
        r = self._live.get(uid)
        if r is None or r.prefill_end_t is not None:
            return
        r.prefill_end_t = max(self.clock(), r.submit_t)
        r.prefill_computed = int(computed_tokens)
        r.prefill_cached = int(cached_tokens)

    def on_spill(self, uid: Any) -> None:
        r = self._live.get(uid)
        if r is not None:
            r.spills += 1

    def on_restore_stall(self, uid: Any, seconds: float) -> None:
        r = self._live.get(uid)
        if r is not None:
            r.spill_stall_s += float(seconds)

    def on_handoff_stall(self, uid: Any, seconds: float) -> None:
        """Receiver-side handoff stall: prefill-replica export to
        decode-replica install, stamped on the DECODE replica's record
        (the stall delays that replica's re-admission of the request)."""
        r = self._live.get(uid)
        if r is not None:
            r.handoff_stall_s += float(seconds)
            r.handoffs += 1

    def on_handoff_out(self, uid: Any) -> Optional[Dict[str, Any]]:
        """Donor-side handoff: the request leaves this (prefill-role)
        replica after its first token.  Closes the record here —
        TTFT/queue-wait/prefill attribute to the prefill role; the
        decode replica's fresh record owns TPOT from its own import."""
        r = self._live.pop(uid, None)
        if r is None:
            return None
        r.finish_t = self.clock()
        self._done.append(r)
        self.handed_off += 1
        rec = self._rec_summary(r)
        for name in ("ttft_ms", "queue_wait_ms", "router_queue_wait_ms",
                     "prefill_ms"):
            v = rec.get(name)
            if v is not None:
                self._observe(name, v)
        return rec

    def on_error(self, uid: Any) -> None:
        """A recoverable per-request fault (e.g. KV restore failure
        forcing re-prefill) — feeds the tail sampler's error arm."""
        r = self._live.get(uid)
        if r is not None:
            r.errors += 1

    def on_cancel(self, uid: Any) -> None:
        """Cancelled mid-flight (client disconnect, deadline): drop the
        live record WITHOUT feeding the percentile series — a cancelled
        request's truncated TTFT/TPOT would skew the tails.  Only the
        count survives."""
        if self._live.pop(uid, None) is not None:
            self.cancelled += 1

    def on_finish(self, uid: Any) -> Optional[Dict[str, Any]]:
        """Completes ``uid`` and returns its summary record (the SLO /
        tail-sampling input) — None if the uid was never submitted."""
        r = self._live.pop(uid, None)
        if r is None:
            return None
        r.finish_t = self.clock()
        self._done.append(r)
        self.finished += 1
        rec = self._rec_summary(r)
        for name in ("ttft_ms", "tpot_ms", "queue_wait_ms",
                     "router_queue_wait_ms", "spill_stall_ms",
                     "prefill_ms", "handoff_stall_ms"):
            v = rec.get(name)
            if v is not None:
                self._observe(name, v)
        return rec

    # -- derived metrics -------------------------------------------------

    @staticmethod
    def _rec_summary(r: _Rec) -> Dict[str, Any]:
        """Per-request scalars; fields absent from the lifecycle stay
        None (``spill_stall_ms`` only exists for requests that actually
        spilled, matching the ``summary()`` series filters)."""
        ttft = ((r.first_token_t - r.submit_t) * 1e3
                if r.first_token_t is not None else None)
        tpot = ((r.last_token_t - r.first_token_t) * 1e3 / (r.tokens - 1)
                if r.tokens >= 2 and r.first_token_t is not None else None)
        return {
            "uid": r.uid,
            "submit_t": r.submit_t,
            "finish_t": r.finish_t,
            "ttft_ms": ttft,
            "tpot_ms": tpot,
            "queue_wait_ms": ((r.admit_t - r.submit_t) * 1e3
                              if r.admit_t is not None else None),
            "router_queue_wait_ms": (
                (r.admit_t - r.router_accept_t) * 1e3
                if r.admit_t is not None
                and r.router_accept_t is not None else None),
            "spill_stall_ms": (r.spill_stall_s * 1e3 if r.spills > 0
                               else None),
            "prefill_ms": ((r.prefill_end_t - r.admit_t) * 1e3
                           if r.prefill_end_t is not None
                           and r.admit_t is not None else None),
            "handoff_stall_ms": (r.handoff_stall_s * 1e3
                                 if r.handoffs > 0 else None),
            "tokens": r.tokens,
            "spills": r.spills,
            "handoffs": r.handoffs,
            "errors": r.errors,
        }

    def completed(self) -> List[Dict[str, Any]]:
        """Summary records for the retained completed-request window."""
        return [self._rec_summary(r) for r in self._done]

    def summary(self) -> Dict[str, Any]:
        """Flat percentile summary over completed requests (ms)."""
        done = list(self._done)
        series: Dict[str, List[float]] = {
            "ttft_ms": [(r.first_token_t - r.submit_t) * 1e3 for r in done
                        if r.first_token_t is not None],
            "tpot_ms": [(r.last_token_t - r.first_token_t) * 1e3
                        / (r.tokens - 1) for r in done
                        if r.tokens >= 2 and r.first_token_t is not None],
            "queue_wait_ms": [(r.admit_t - r.submit_t) * 1e3 for r in done
                              if r.admit_t is not None],
            "router_queue_wait_ms": [
                (r.admit_t - r.router_accept_t) * 1e3 for r in done
                if r.admit_t is not None
                and r.router_accept_t is not None],
            "spill_stall_ms": [r.spill_stall_s * 1e3 for r in done
                               if r.spills > 0],
            "prefill_ms": [(r.prefill_end_t - r.admit_t) * 1e3
                           for r in done
                           if r.prefill_end_t is not None
                           and r.admit_t is not None],
            "handoff_stall_ms": [r.handoff_stall_s * 1e3 for r in done
                                 if r.handoffs > 0],
        }
        out: Dict[str, Any] = {"completed": len(done),
                               "submitted": self.submitted,
                               "cancelled": self.cancelled,
                               "handed_off": self.handed_off,
                               "in_flight": len(self._live),
                               "prefill_computed_tokens": sum(
                                   r.prefill_computed for r in done),
                               "prefill_cached_tokens": sum(
                                   r.prefill_cached for r in done)}
        for name, vals in series.items():
            for q in self.PCTS:
                v = percentile(vals, q)
                out[f"{name}_p{q}"] = (None if v is None
                                       else round(v, 4))
        return out
