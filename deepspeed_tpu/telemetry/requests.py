"""Per-request serving latency: TTFT / TPOT / queue-wait / spill-stall.

The ragged engine reports throughput (tok/s) but scale-out serving is
gated on per-request percentiles — a batch that sustains 20k tok/s can
still starve one request behind a spill storm.  The engine feeds this
tracker from its lifecycle hooks (submit → admit → token folds →
reap); ``summary()`` derives nearest-rank p50/p90/p99 over completed
requests and returns a FLAT dict (``MonitorMaster.write_serving_health``
flattens exactly one level of sub-dicts, so the shape must already be
scalar-valued).

Semantics under the pipelined host path: token timestamps are taken at
HARVEST (when the host folds device tokens back into request state) —
the honest host-visible latency, since the deferred-harvest pipeline
means the host cannot observe a token earlier than that.

- ``ttft``: first harvested token − submit (clamped at submit — a
  prefix-cache hit whose prefill is fully skipped can emit in the same
  scheduler tick it was admitted; the sample must be ≥ 0, never
  missing or negative)
- ``tpot``: (last − first token) / (tokens − 1), requests with ≥2 tokens
- ``queue_wait``: first admit − submit
- ``spill_stall``: accumulated restore-bracket seconds per request
- ``prefill``: admit → prefill-complete span, plus per-request counts
  of prefill tokens actually computed vs skipped via the prefix cache
  (a full prefix hit records a ~zero-length span, not a hole)

The tracker is always on (a few dict ops per request per harvest —
noise next to a device dispatch), independent of the tracer's enabled
flag, so the bench ragged row always carries ``request_latency``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["RequestLatencyTracker", "percentile"]


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (ceil(q/100 * n)-th smallest) — hand
    computable for test fixtures; no interpolation."""
    if not values:
        return None
    vs = sorted(values)
    n = len(vs)
    rank = max(1, -(-int(q * n) // 100))          # ceil(q*n/100), >= 1
    return vs[min(rank, n) - 1]


class _Rec:
    __slots__ = ("submit_t", "admit_t", "first_token_t", "last_token_t",
                 "tokens", "spill_stall_s", "spills", "finish_t",
                 "prefill_end_t", "prefill_computed", "prefill_cached")

    def __init__(self, submit_t: float):
        self.submit_t = submit_t
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.tokens = 0
        self.spill_stall_s = 0.0
        self.spills = 0
        self.finish_t: Optional[float] = None
        self.prefill_end_t: Optional[float] = None
        self.prefill_computed = 0
        self.prefill_cached = 0


class RequestLatencyTracker:
    """Lifecycle-fed latency percentiles, keyed by request uid."""

    PCTS = (50, 90, 99)

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 max_completed: int = 4096):
        self.clock = clock
        self._live: Dict[Any, _Rec] = {}
        self._done: deque = deque(maxlen=max_completed)
        self.submitted = 0
        self.finished = 0

    # -- lifecycle hooks (called by the engine) --------------------------

    def on_submit(self, uid: Any) -> None:
        self._live[uid] = _Rec(self.clock())
        self.submitted += 1

    def on_admit(self, uid: Any) -> None:
        r = self._live.get(uid)
        if r is not None and r.admit_t is None:   # first admit only —
            r.admit_t = self.clock()              # re-admits after evict
            pass                                  # are not queue wait

    def on_tokens(self, uid: Any, total_tokens: int) -> None:
        """``total_tokens`` is the request's cumulative generated count
        (idempotent — repeated calls with an unchanged count are no-ops)."""
        r = self._live.get(uid)
        if r is None or total_tokens <= r.tokens:
            return
        # clamp at submit so a fully-skipped prefill (prefix-cache hit
        # emitting in its admission tick) records TTFT >= 0 even under
        # a coarse injected clock
        now = max(self.clock(), r.submit_t)
        if r.first_token_t is None:
            r.first_token_t = now
        r.last_token_t = now
        r.tokens = total_tokens

    def on_prefill_done(self, uid: Any, computed_tokens: int,
                        cached_tokens: int = 0) -> None:
        """Prefill finished for ``uid``: ``computed_tokens`` went
        through the model, ``cached_tokens`` were skipped via the
        prefix cache.  First call wins (evict/re-prefill churn keeps
        the original span)."""
        r = self._live.get(uid)
        if r is None or r.prefill_end_t is not None:
            return
        r.prefill_end_t = max(self.clock(), r.submit_t)
        r.prefill_computed = int(computed_tokens)
        r.prefill_cached = int(cached_tokens)

    def on_spill(self, uid: Any) -> None:
        r = self._live.get(uid)
        if r is not None:
            r.spills += 1

    def on_restore_stall(self, uid: Any, seconds: float) -> None:
        r = self._live.get(uid)
        if r is not None:
            r.spill_stall_s += float(seconds)

    def on_finish(self, uid: Any) -> None:
        r = self._live.pop(uid, None)
        if r is None:
            return
        r.finish_t = self.clock()
        self._done.append(r)
        self.finished += 1

    # -- derived metrics -------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """Flat percentile summary over completed requests (ms)."""
        done = list(self._done)
        series: Dict[str, List[float]] = {
            "ttft_ms": [(r.first_token_t - r.submit_t) * 1e3 for r in done
                        if r.first_token_t is not None],
            "tpot_ms": [(r.last_token_t - r.first_token_t) * 1e3
                        / (r.tokens - 1) for r in done
                        if r.tokens >= 2 and r.first_token_t is not None],
            "queue_wait_ms": [(r.admit_t - r.submit_t) * 1e3 for r in done
                              if r.admit_t is not None],
            "spill_stall_ms": [r.spill_stall_s * 1e3 for r in done
                               if r.spills > 0],
            "prefill_ms": [(r.prefill_end_t - r.admit_t) * 1e3
                           for r in done
                           if r.prefill_end_t is not None
                           and r.admit_t is not None],
        }
        out: Dict[str, Any] = {"completed": len(done),
                               "submitted": self.submitted,
                               "in_flight": len(self._live),
                               "prefill_computed_tokens": sum(
                                   r.prefill_computed for r in done),
                               "prefill_cached_tokens": sum(
                                   r.prefill_cached for r in done)}
        for name, vals in series.items():
            for q in self.PCTS:
                v = percentile(vals, q)
                out[f"{name}_p{q}"] = (None if v is None
                                       else round(v, 4))
        return out
