"""Unified tracing + flight recorder.

One span schema under every telemetry dialect in the tree:

- ``trace`` — the process-wide :class:`~.tracer.Tracer` singleton.
  ``with trace.span("swap_in_wait", bucket=3): ...`` when enabled;
  a no-op singleton context manager (zero allocation) when disabled.
- ``trace.export(path)`` — Chrome trace-event JSON for
  https://ui.perfetto.dev.
- ``flight.dump_on_fault(reason, exc)`` — dump the bounded span ring
  to a self-describing JSONL on hard-failure paths.
- :class:`RequestLatencyTracker` — per-request TTFT/TPOT/queue-wait/
  spill-stall percentiles for the serving engines.

Enable knobs: ``DSTPU_TRACE=1`` (env) or
``telemetry.configure(enabled=True)``; ``DSTPU_TRACE_BUFFER`` sizes
the per-thread rings; ``DSTPU_TRACE_ANNOTATE=1`` bridges spans into
``jax.profiler`` device profiles; ``DSTPU_FLIGHT_DIR`` picks the
flight-dump directory.

Stdlib-only on import (jax is lazy) — safe to import from every layer.
"""
from deepspeed_tpu.telemetry.tracer import (Tracer, configure, get_tracer,
                                            trace)
from deepspeed_tpu.telemetry import flight
from deepspeed_tpu.telemetry.flight import (dump_on_fault, last_dump_path,
                                            read_flight_record)
from deepspeed_tpu.telemetry.requests import (RequestLatencyTracker,
                                              percentile)

__all__ = ["Tracer", "trace", "get_tracer", "configure", "flight",
           "dump_on_fault", "last_dump_path", "read_flight_record",
           "RequestLatencyTracker", "percentile"]
