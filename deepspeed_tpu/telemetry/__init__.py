"""Unified tracing, flight recorder, and production metrics.

One span schema + one metrics registry under every telemetry dialect
in the tree:

- ``trace`` — the process-wide :class:`~.tracer.Tracer` singleton.
  ``with trace.span("swap_in_wait", bucket=3): ...`` when enabled;
  a no-op singleton context manager (zero allocation) when disabled.
- ``trace.export(path)`` — Chrome trace-event JSON for
  https://ui.perfetto.dev.  With tail sampling armed
  (``DSTPU_TRACE_SAMPLE=N``), only *promoted* request timelines (SLO
  breach / error / deterministic 1-in-N) are exported.
- ``flight.dump_on_fault(reason, exc)`` — dump the bounded span ring
  (plus a cumulative metrics snapshot) to a self-describing JSONL on
  hard-failure paths.
- :class:`RequestLatencyTracker` — per-request TTFT/TPOT/queue-wait/
  spill-stall percentiles for the serving engines; feeds the metrics
  histograms automatically.
- ``metrics.metrics`` — the :class:`~.metrics.MetricsRegistry`
  singleton: counters/gauges/exponential histograms with per-thread
  shards, ``export_text()`` (Prometheus exposition) and
  ``export_json()``.
- :class:`~.slo.SLOSet` / :class:`~.slo.TailSampler` — objectives like
  ``"ttft_ms_p99 <= 150"`` with rolling-window error-budget burn rate,
  and the tail-sampling promotion policy.
- :mod:`~.profiler` — per-program device seconds from XPlane traces
  (the host-vs-device split for bench rows).

Enable knobs: ``DSTPU_TRACE=1`` (env) or
``telemetry.configure(enabled=True)``; ``DSTPU_TRACE_BUFFER`` sizes
the per-thread rings; ``DSTPU_TRACE_SAMPLE=N`` arms tail sampling;
``DSTPU_TRACE_ANNOTATE=1`` bridges spans into ``jax.profiler`` device
profiles; ``DSTPU_FLIGHT_DIR`` picks the flight-dump directory;
``DSTPU_METRICS=0`` disables the metrics registry.

Stdlib-only on import (jax is lazy) — safe to import from every layer.
"""
from deepspeed_tpu.telemetry.tracer import (Tracer, configure, get_tracer,
                                            trace)
from deepspeed_tpu.telemetry import metrics
from deepspeed_tpu.telemetry.metrics import (MetricsRegistry,
                                             exponential_buckets,
                                             get_registry,
                                             validate_metrics_doc)
from deepspeed_tpu.telemetry.slo import (Objective, SLOSet, TailSampler,
                                         parse_objective)
from deepspeed_tpu.telemetry import flight
from deepspeed_tpu.telemetry.flight import (dump_on_fault, last_dump_path,
                                            read_flight_record)
from deepspeed_tpu.telemetry.requests import (RequestLatencyTracker,
                                              percentile)
from deepspeed_tpu.telemetry import profiler

__all__ = ["Tracer", "trace", "get_tracer", "configure", "flight",
           "dump_on_fault", "last_dump_path", "read_flight_record",
           "RequestLatencyTracker", "percentile",
           "metrics", "MetricsRegistry", "exponential_buckets",
           "get_registry", "validate_metrics_doc",
           "Objective", "SLOSet", "TailSampler", "parse_objective",
           "profiler"]
