"""jax.profiler bridge: per-program device time from XPlane traces.

PR 6 recorded the blind spot verbatim in the bench row — "draft_ms/
verify_ms [host brackets — device split needs the profiler]".  The host
brackets around a pipelined dispatch measure handoff, not execution, so
the speculation economics (is verify device time the cost, or host
scheduling?) were unanswerable.  This module closes it: after a run
profiled with ``jax.profiler.start_trace(dir)``, it reads the newest
``*.xplane.pb`` and aggregates device-plane event durations *per jitted
program name* (``jit_<fn.__name__>``) — the engine names its jitted
closures distinguishably (``ragged_decode_block``, ``spec_verify_block``,
``draft_prefill``, ...) exactly so this attribution works.

Graceful everywhere: on CPU-only smoke runs there are no device planes
and :func:`device_seconds_by_program` returns ``{}``; callers render
``source: None`` instead of fake numbers.  Multi-chip hosts average
over planes (same convention as bench's aggregate device-seconds
helper) so one logical dispatch isn't counted once per chip.
"""
from __future__ import annotations

import glob
import os
from typing import Dict, Optional

__all__ = ["device_seconds_by_program", "split_host_device"]


def device_seconds_by_program(trace_dir: str, prefix: str = "jit_",
                              ) -> Dict[str, float]:
    """``{program_name: device_seconds}`` from the newest xplane under
    ``trace_dir``.  Prefers TPU planes; falls back to GPU planes, then
    to any plane carrying ``prefix`` events.  ``{}`` when no profile or
    no device events exist (never raises)."""
    try:
        from jax.profiler import ProfileData
    except Exception:
        return {}
    try:
        paths = sorted(glob.glob(os.path.join(trace_dir, "**",
                                              "*.xplane.pb"),
                                 recursive=True))
        if not paths:
            return {}
        pdata = ProfileData.from_file(paths[-1])
        planes = list(pdata.planes)
    except Exception:
        return {}

    def _collect(selector) -> Dict[str, float]:
        per_prog: Dict[str, float] = {}
        n_planes = 0
        for plane in planes:
            if not selector(plane.name):
                continue
            plane_progs: Dict[str, float] = {}
            try:
                for line in plane.lines:
                    for ev in line.events:
                        if ev.name.startswith(prefix):
                            plane_progs[ev.name] = (
                                plane_progs.get(ev.name, 0.0)
                                + ev.duration_ns / 1e9)
            except Exception:
                continue
            if plane_progs:
                n_planes += 1
                for k, v in plane_progs.items():
                    per_prog[k] = per_prog.get(k, 0.0) + v
        if n_planes > 1:              # average over chips, like bench
            per_prog = {k: v / n_planes for k, v in per_prog.items()}
        return per_prog

    for sel in (lambda n: "TPU" in n,
                lambda n: "GPU" in n or "gpu" in n,
                lambda n: True):
        out = _collect(sel)
        if out:
            return out
    return {}


def device_seconds_matching(progs: Dict[str, float], substr: str) -> float:
    """Sum device seconds over programs whose name contains ``substr``
    (XLA may suffix recompiled programs, so exact match is too brittle)."""
    return sum(v for k, v in progs.items() if substr in k)


__all__.append("device_seconds_matching")


def split_host_device(host_s: float, device_s: Optional[float]
                      ) -> Dict[str, Optional[float]]:
    """Render a host-bracketed interval against its attributed device
    time.  ``host_other_s`` is the bracket residual (scheduling, Python,
    transfer setup); negative residuals clamp to 0 — under the pipelined
    dispatch the host bracket releases before the device finishes, so
    device > bracket is expected, not an error."""
    if device_s is None:
        return {"host_s": host_s, "device_s": None, "host_other_s": None}
    return {"host_s": host_s, "device_s": device_s,
            "host_other_s": max(0.0, host_s - device_s)}
