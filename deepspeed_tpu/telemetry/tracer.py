"""Span-based structured tracer — the single substrate under all
existing telemetry dialects.

Design constraints (the reason this is NOT just another timer class):

- **Near-zero cost when disabled.**  ``trace.span(...)`` on a disabled
  tracer returns a process-wide singleton no-op context manager: no
  allocation, no string formatting, no clock read.  Adapters in the
  legacy telemetry (``HostStageStats``, ``StageTimers``,
  ``utils/timer.py``) guard their re-emit with ``if trace.enabled``,
  so tracing off means the hot paths behave byte-for-byte as before.
- **Thread-aware.**  Every span/event lands in a bounded per-thread
  ring (``collections.deque(maxlen=...)``); threads never contend on a
  lock in the record path (the lock only guards ring *registration*).
  The serving host path, AIO callback threads, and the SDC digest pool
  each get their own timeline row in the exported trace.
- **Injectable clock.**  ``configure(clock=...)`` swaps the monotonic
  source so tests drive deterministic timestamps.  The default is
  ``time.perf_counter`` — the same clock every legacy dialect already
  uses, which lets adapters hand us externally bracketed intervals
  (``add_complete``) without a unit conversion.
- **Standard viewer format.**  ``export(path)`` writes Chrome
  trace-event JSON (the ``{"traceEvents": [...]}`` object form) that
  opens directly in https://ui.perfetto.dev or ``chrome://tracing``.
- **Flight-recorder substrate.**  The bounded rings double as the
  postmortem buffer: ``snapshot()`` hands the recent timeline to
  ``telemetry.flight.dump_on_fault`` on hard-failure paths.

The module is stdlib-only (``jax`` imported lazily for the optional
``TraceAnnotation`` bridge) so every layer of the codebase — comm
watchdog, resilience guards, swap path, serving engines — can import
it without cycles.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Tracer", "get_tracer", "configure", "trace"]

DEFAULT_BUFFER = 8192          # spans+events retained per thread
_SCHEMA_VERSION = 1


class _NullSpan:
    """Singleton no-op context manager — the disabled-tracer fast path.

    ``__slots__ = ()`` + module-level singleton means a disabled
    ``trace.span(...)`` call allocates nothing and formats nothing.
    """

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """A live span: records one complete ("X") event on exit."""

    __slots__ = ("_tracer", "name", "cat", "attrs", "t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.t0 = 0.0
        self._ann = None

    def __enter__(self):
        tr = self._tracer
        self.t0 = tr.clock()
        if tr.annotate:
            ann = tr._annotation_cls()
            if ann is not None:
                self._ann = ann(self.name)
                self._ann.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        tr = self._tracer
        t1 = tr.clock()
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        attrs = self.attrs
        if exc_type is not None:
            attrs = dict(attrs) if attrs else {}
            attrs["error"] = exc_type.__name__
        tr._append({
            "ph": "X", "name": self.name, "cat": self.cat,
            "ts": tr._us(self.t0), "dur": max(0.0, (t1 - self.t0) * 1e6),
            "args": attrs or {},
        })
        return False


class Tracer:
    """Thread-aware span recorder with bounded per-thread rings.

    One process-wide instance lives at ``telemetry.trace``; tests build
    private instances with injected clocks.  All mutation of an
    existing instance goes through :meth:`configure` so modules that
    did ``from deepspeed_tpu.telemetry import trace`` at import time
    observe runtime enable/disable.
    """

    def __init__(self, enabled: bool = False,
                 buffer_size: int = DEFAULT_BUFFER,
                 clock: Callable[[], float] = time.perf_counter,
                 annotate: bool = False,
                 sampling: bool = False,
                 sample_n: int = 0,
                 retained_size: int = 4 * DEFAULT_BUFFER):
        self.enabled = bool(enabled)
        self.buffer_size = int(buffer_size)
        self.clock = clock
        self.annotate = bool(annotate)
        # Tail sampling: record always-on into the per-thread staging
        # rings, but treat them as scratch — only spans *promoted* (the
        # request breached an SLO, errored, or fell in the 1-in-N
        # sample) survive into the bounded retained ring that export()
        # writes.  ``sample_n`` is the engine-consumed default N.
        self.sampling = bool(sampling)
        self.sample_n = int(sample_n)
        self.retained_size = int(retained_size)
        self._retained: deque = deque(maxlen=self.retained_size)
        self._epoch = clock()
        self._lock = threading.Lock()
        self._rings: Dict[int, deque] = {}
        self._thread_names: Dict[int, str] = {}
        self._local = threading.local()
        self._annotation = None      # resolved lazily, cached

    # -- configuration ---------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  buffer_size: Optional[int] = None,
                  clock: Optional[Callable[[], float]] = None,
                  annotate: Optional[bool] = None,
                  sampling: Optional[bool] = None,
                  sample_n: Optional[int] = None,
                  retained_size: Optional[int] = None) -> "Tracer":
        """Mutate in place (never replace — importers hold references)."""
        with self._lock:
            if clock is not None:
                self.clock = clock
                self._epoch = clock()
            if buffer_size is not None and buffer_size != self.buffer_size:
                self.buffer_size = int(buffer_size)
                for tid, ring in list(self._rings.items()):
                    self._rings[tid] = deque(ring, maxlen=self.buffer_size)
                self._local = threading.local()
            if annotate is not None:
                self.annotate = bool(annotate)
            if sampling is not None:
                self.sampling = bool(sampling)
            if sample_n is not None:
                self.sample_n = int(sample_n)
            if retained_size is not None \
                    and int(retained_size) != self.retained_size:
                self.retained_size = int(retained_size)
                self._retained = deque(self._retained,
                                       maxlen=self.retained_size)
            if enabled is not None:
                self.enabled = bool(enabled)
        return self

    # -- record path -----------------------------------------------------

    def span(self, name: str, cat: str = "host", **attrs):
        """``with trace.span("swap_in_wait", bucket=3): ...``

        Disabled: returns the shared no-op singleton (no allocation)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, attrs or None)

    def event(self, name: str, cat: str = "host", **attrs) -> None:
        """Instant event (Chrome ``ph: "i"``) — request lifecycle marks."""
        if not self.enabled:
            return
        self._append({"ph": "i", "name": name, "cat": cat, "s": "t",
                      "ts": self._us(self.clock()), "args": attrs or {}})

    def add_complete(self, name: str, start: float, dur_s: float,
                     cat: str = "host", **attrs) -> None:
        """Record an externally bracketed interval (the adapter entry
        point for legacy timers that already hold t0/dt from the SAME
        clock as the tracer — ``time.perf_counter`` by default)."""
        if not self.enabled:
            return
        self._append({"ph": "X", "name": name, "cat": cat,
                      "ts": self._us(start),
                      "dur": max(0.0, dur_s * 1e6), "args": attrs or {}})

    def _append(self, ev: Dict[str, Any]) -> None:
        ring = getattr(self._local, "ring", None)
        if ring is None or ring.maxlen != self.buffer_size:
            t = threading.current_thread()
            with self._lock:
                ring = self._rings.get(t.ident)
                if ring is None or ring.maxlen != self.buffer_size:
                    ring = deque(maxlen=self.buffer_size)
                    self._rings[t.ident] = ring
                self._thread_names[t.ident] = t.name
            self._local.ring = ring
        ev["tid"] = threading.get_ident()
        ring.append(ev)

    def _us(self, t: float) -> float:
        return (t - self._epoch) * 1e6

    def _annotation_cls(self):
        """``jax.profiler.TraceAnnotation`` when available, else None —
        bridges host spans into the device profile timeline."""
        if self._annotation is None:
            try:
                from jax.profiler import TraceAnnotation
                self._annotation = TraceAnnotation
            except Exception:
                self._annotation = False
        return self._annotation or None

    # -- read path -------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """Recent spans+events across all threads, ts-sorted (the
        flight-recorder view — cheap enough for a failure path)."""
        with self._lock:
            rings = [(tid, list(ring)) for tid, ring in self._rings.items()]
        out: List[Dict[str, Any]] = []
        for _tid, evs in rings:
            out.extend(evs)
        out.sort(key=lambda e: e.get("ts", 0.0))
        return out

    # -- tail sampling ---------------------------------------------------

    def promote(self, uid: Any, t0: float, t1: float, reason: str = "",
                slack_s: float = 0.05) -> int:
        """Copy one request's timeline from the staging rings into the
        retained ring (tail-based sampling: called at reap time when the
        request breached an SLO, errored, or won the 1-in-N draw).

        ``t0``/``t1`` are raw clock seconds (the tracker's ``submit_t``
        / ``finish_t`` — same ``perf_counter`` clock as the tracer);
        ``slack_s`` widens the window so the reap event recorded just
        after ``on_finish`` still lands.  Selection keeps every span
        overlapping the window EXCEPT request-lifecycle events that
        belong to *other* uids — so a promoted slow request carries the
        shared serving spans (prefill chunks, decode blocks it rode in)
        but not its neighbours' lifecycles, and un-promoted fast
        requests leave no lifecycle marks in the retained ring.
        Returns the number of events promoted."""
        t0_us = self._us(t0) - slack_s * 1e6
        t1_us = self._us(t1) + slack_s * 1e6
        kept: List[Dict[str, Any]] = []
        for ev in self.snapshot():
            ts = ev.get("ts", 0.0)
            end = ts + ev.get("dur", 0.0)
            if end < t0_us or ts > t1_us:
                continue
            if ev.get("cat") == "request":
                args = ev.get("args") or {}
                if args.get("uid") != uid and \
                        uid not in (args.get("uids") or ()):
                    continue
            kept.append(ev)
        marker = {"ph": "i", "name": "promoted", "cat": "sampling",
                  "s": "t", "ts": self._us(self.clock()),
                  "tid": threading.get_ident(),
                  "args": {"uid": uid, "reason": reason,
                           "events": len(kept)}}
        with self._lock:
            self._retained.extend(kept)
            self._retained.append(marker)
        return len(kept)

    def retained_snapshot(self) -> List[Dict[str, Any]]:
        """Promoted events (ts-sorted) — what export() writes when
        sampling is armed."""
        with self._lock:
            out = list(self._retained)
        out.sort(key=lambda e: e.get("ts", 0.0))
        return out

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()
            self._thread_names.clear()
            self._local = threading.local()
            self._retained.clear()

    def export(self, path: str) -> str:
        """Write Chrome trace-event JSON (object form) to ``path``.

        Opens in https://ui.perfetto.dev / ``chrome://tracing``.  Adds
        process/thread-name metadata events so timeline rows are
        labelled.  With tail sampling armed, only the *promoted*
        timeline (the retained ring) is written — the staging rings are
        scratch."""
        pid = os.getpid()
        events: List[Dict[str, Any]] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": f"deepspeed_tpu pid={pid}"},
        }]
        with self._lock:
            names = dict(self._thread_names)
        for tid, tname in sorted(names.items()):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "ts": 0, "args": {"name": tname}})
        body = self.retained_snapshot() if self.sampling else self.snapshot()
        for ev in body:
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"schema": "deepspeed_tpu.telemetry",
                             "version": _SCHEMA_VERSION}}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return path


# ---------------------------------------------------------------------------
# Process-wide singleton
# ---------------------------------------------------------------------------

def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes",
                                                        "on")


def _env_sample_n() -> Optional[int]:
    """``DSTPU_TRACE_SAMPLE=N`` arms tail sampling with a 1-in-N random
    arm (N=0: promote only on SLO breach / error).  Unset: disarmed."""
    raw = os.environ.get("DSTPU_TRACE_SAMPLE", "").strip()
    if not raw:
        return None
    try:
        return max(0, int(raw))
    except ValueError:
        return None


_SAMPLE_N = _env_sample_n()

trace = Tracer(
    enabled=_env_truthy("DSTPU_TRACE") or _SAMPLE_N is not None,
    buffer_size=int(os.environ.get("DSTPU_TRACE_BUFFER", DEFAULT_BUFFER)),
    annotate=_env_truthy("DSTPU_TRACE_ANNOTATE"),
    sampling=_SAMPLE_N is not None,
    sample_n=_SAMPLE_N or 0,
)


def get_tracer() -> Tracer:
    return trace


def configure(**kw) -> Tracer:
    """``telemetry.configure(enabled=True, buffer_size=..., clock=...,
    annotate=...)`` — mutates the process singleton in place."""
    return trace.configure(**kw)
