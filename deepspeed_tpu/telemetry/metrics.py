"""Production metrics registry: counters, gauges, exponential histograms.

PR 10's tracer answers "what happened during THIS window" (bounded span
rings, flight dumps); this module answers "how is the process doing,
cumulatively" — the signal plane the scale-out router, admission
controller, and closed-loop autotuner consume.  Design mirrors the
tracer's constraints:

- **Lock-free record path.**  Every metric child keeps *per-thread
  shards* (a tiny mutable cell registered once per thread under the
  registry lock, then mutated without any lock — safe under the GIL
  because each shard has exactly one writer).  The serving host path,
  AIO callback threads, and the SDC digest pool never contend; reads
  (``export_*``/``quantile``) merge shards at call time.
- **Near-zero cost when disabled.**  Emitters guard with
  ``if metrics.enabled`` (same idiom as ``if trace.enabled``); the
  singleton ships enabled unless ``DSTPU_METRICS=0``.
- **Injectable clock** (``configure(clock=...)``) so tests pin
  ``unix_time`` in exports.
- **Hand-computable histograms.**  Fixed exponential bucket bounds
  (``exponential_buckets``), quantiles by linear interpolation inside
  the crossing bucket — both derivable on paper for test fixtures, and
  guaranteed within one bucket width of the nearest-rank percentiles
  ``RequestLatencyTracker`` reports (serve_smoke gates this).
- **Scrapeable.**  ``export_text()`` emits Prometheus exposition format
  (``# HELP``/``# TYPE``, cumulative ``_bucket{le=...}`` series,
  ``_sum``/``_count``); ``export_json()`` a self-describing
  ``{"record": "metrics"}`` document that flight dumps embed and
  ``trace_summarize --metrics`` renders.

Stdlib-only, import-cycle-free: anything from the comm watchdog to the
swap path can feed it.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "exponential_buckets", "get_registry", "metrics", "configure",
]

_SCHEMA_VERSION = 1
INF = float("inf")


def exponential_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` upper bounds ``start * factor**i`` (``+Inf`` is implicit).

    >>> exponential_buckets(1.0, 2.0, 4)
    (1.0, 2.0, 4.0, 8.0)
    """
    if start <= 0 or factor <= 1.0 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


# Default bucket layouts.  Milliseconds: 0.01 ms .. ~168 s covers TPOT
# fractions-of-ms through queue waits of minutes.  Seconds: 10 µs .. ~84 s
# covers stage brackets from a host dict-op to an NVMe restore storm.
MS_BUCKETS = exponential_buckets(0.01, 2.0, 24)
SECONDS_BUCKETS = exponential_buckets(1e-5, 2.0, 23)


def _fmt(v: float) -> str:
    """Exposition-format number: integral floats render without the
    trailing ``.0`` noise, everything else via repr (full precision)."""
    if v == INF:
        return "+Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels.items())
    return "{" + inner + "}"


class _Child:
    """Base for one (metric, label-values) time series."""

    __slots__ = ("name", "labels", "_lock", "_shards", "_local")

    def __init__(self, name: str, labels: Dict[str, str], lock: threading.Lock):
        self.name = name
        self.labels = labels
        self._lock = lock                 # registry lock, registration only
        self._shards: Dict[int, Any] = {}
        self._local = threading.local()

    def _shard(self):
        s = getattr(self._local, "shard", None)
        if s is None:
            s = self._new_shard()
            with self._lock:
                self._shards[threading.get_ident()] = s
            self._local.shard = s
        return s

    def _all_shards(self) -> List[Any]:
        with self._lock:
            return list(self._shards.values())

    def _new_shard(self):            # pragma: no cover - abstract
        raise NotImplementedError


class _CounterShard:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Counter(_Child):
    """Monotonic counter.  ``inc(n)`` on the calling thread's shard;
    ``set_total(v)`` mirrors an *external* cumulative counter (e.g. the
    swapper's ``sdc_counters`` dict) — monotonic max, single logical
    writer; don't mix the two styles on one child."""

    __slots__ = ("_abs",)

    def __init__(self, name, labels, lock):
        super().__init__(name, labels, lock)
        self._abs: Optional[float] = None

    def _new_shard(self):
        return _CounterShard()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        self._shard().value += n

    def set_total(self, v: float) -> None:
        cur = self._abs
        self._abs = float(v) if cur is None else max(cur, float(v))

    def value(self) -> float:
        if self._abs is not None:
            return self._abs
        return sum(s.value for s in self._all_shards())


class _GaugeShard:
    __slots__ = ("value", "stamp")

    def __init__(self):
        self.value = 0.0
        self.stamp = 0


class Gauge(_Child):
    """Last-write-wins gauge.  ``set()`` stamps the writing shard with a
    global sequence number so the merged read returns the most recent
    write across threads; ``add()`` accumulates (merged read sums)."""

    _seq = [0]  # class-level monotonic stamp; GIL-atomic enough for telemetry

    def _new_shard(self):
        return _GaugeShard()

    def set(self, v: float) -> None:
        s = self._shard()
        Gauge._seq[0] += 1
        s.stamp = Gauge._seq[0]
        s.value = float(v)

    def add(self, n: float = 1.0) -> None:
        s = self._shard()
        s.value += n
        s.stamp = -1                       # additive shards merge by sum

    def value(self) -> float:
        shards = self._all_shards()
        if not shards:
            return 0.0
        if any(s.stamp == -1 for s in shards):
            return sum(s.value for s in shards)
        live = max(shards, key=lambda s: s.stamp)
        return live.value


class _HistShard:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, nbuckets: int):
        self.counts = [0] * (nbuckets + 1)   # +1 for the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Child):
    """Fixed-bucket histogram; bounds are *upper* bucket edges plus an
    implicit ``+Inf``.  Observation is a binary search + three scalar
    writes on the thread's own shard — no lock, no allocation."""

    __slots__ = ("bounds",)

    def __init__(self, name, labels, lock, bounds: Sequence[float]):
        super().__init__(name, labels, lock)
        b = tuple(float(x) for x in bounds)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError("bucket bounds must be strictly increasing")
        self.bounds = b

    def _new_shard(self):
        return _HistShard(len(self.bounds))

    def observe(self, v: float) -> None:
        s = self._shard()
        bounds = self.bounds
        lo, hi = 0, len(bounds)
        while lo < hi:                       # first bound >= v
            mid = (lo + hi) // 2
            if bounds[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        s.counts[lo] += 1
        s.sum += v
        s.count += 1

    # -- merged reads ----------------------------------------------------

    def merged(self) -> Tuple[List[int], float, int]:
        counts = [0] * (len(self.bounds) + 1)
        total_sum, total_n = 0.0, 0
        for s in self._all_shards():
            for i, c in enumerate(s.counts):
                counts[i] += c
            total_sum += s.sum
            total_n += s.count
        return counts, total_sum, total_n

    def count(self) -> int:
        return self.merged()[2]

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-th percentile (``q`` in [0, 100]) by linear
        interpolation inside the crossing bucket.  Hand-computable:
        target rank = q/100 * count; walk cumulative counts; interpolate
        between the bucket's lower and upper bound by the fraction of
        the bucket's population below the target.  Values beyond the
        last finite bound clamp to it (the +Inf bucket has no width)."""
        counts, _s, n = self.merged()
        if n == 0:
            return None
        target = (q / 100.0) * n
        if target <= 0:
            target = min(1.0, float(n))
        cum = 0.0
        lower = 0.0
        for i, ub in enumerate(self.bounds):
            c = counts[i]
            if c and cum + c >= target:
                frac = (target - cum) / c
                return lower + (ub - lower) * frac
            cum += c
            lower = ub
        return self.bounds[-1]

    def bucket_width_at(self, v: float) -> float:
        """Width of the bucket containing ``v`` (the agreement tolerance
        serve_smoke uses: histogram quantile vs nearest-rank sample)."""
        lower = 0.0
        for ub in self.bounds:
            if v <= ub:
                return ub - lower
            lower = ub
        return self.bounds[-1] - (self.bounds[-2] if len(self.bounds) > 1
                                  else 0.0)


class _Family:
    """One metric name: type, help text, label schema, child per label
    combination.  Label-less use goes through the implicit ``()`` child
    (``family.inc()`` etc. proxy to it)."""

    __slots__ = ("name", "kind", "help", "label_names", "_lock", "_children",
                 "_bounds")

    def __init__(self, name: str, kind: str, help_: str,
                 label_names: Tuple[str, ...], lock: threading.Lock,
                 bounds: Optional[Sequence[float]] = None):
        self.name = name
        self.kind = kind
        self.help = help_
        self.label_names = label_names
        self._lock = lock
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._bounds = tuple(bounds) if bounds is not None else None

    def labels(self, **kv: Any) -> _Child:
        if tuple(sorted(kv)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got "
                f"{tuple(sorted(kv))}")
        key = tuple(str(kv[k]) for k in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make(dict(zip(self.label_names, key)))
                    self._children[key] = child
        return child

    def _default(self) -> _Child:
        if self.label_names:
            raise ValueError(f"{self.name} is labelled; use .labels(...)")
        return self.labels()

    def _make(self, labels: Dict[str, str]) -> _Child:
        if self.kind == "counter":
            return Counter(self.name, labels, self._lock)
        if self.kind == "gauge":
            return Gauge(self.name, labels, self._lock)
        return Histogram(self.name, labels, self._lock, self._bounds)

    # label-less convenience proxies
    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def set_total(self, v: float) -> None:
        self._default().set_total(v)

    def set(self, v: float) -> None:
        self._default().set(v)

    def add(self, n: float = 1.0) -> None:
        self._default().add(n)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def value(self) -> float:
        return self._default().value()

    def quantile(self, q: float) -> Optional[float]:
        return self._default().quantile(q)

    def children(self) -> List[_Child]:
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]


class MetricsRegistry:
    """Process metrics: families keyed by name, Prometheus/JSON export.

    One process-wide instance lives at ``telemetry.metrics.metrics``
    (module attribute ``metrics`` below); tests build private instances.
    Like the tracer, runtime reconfiguration mutates the singleton in
    place so importers holding a reference observe it.
    """

    def __init__(self, enabled: bool = True,
                 clock: Callable[[], float] = time.time):
        self.enabled = bool(enabled)
        self.clock = clock
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self.slo: Optional[Any] = None    # SLOSet attached by the engine

    def configure(self, enabled: Optional[bool] = None,
                  clock: Optional[Callable[[], float]] = None
                  ) -> "MetricsRegistry":
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if clock is not None:
                self.clock = clock
        return self

    # -- registration ----------------------------------------------------

    def _family(self, name: str, kind: str, help_: str,
                labels: Sequence[str], bounds=None) -> _Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind:
                raise ValueError(
                    f"{name} already registered as {fam.kind}, not {kind}")
            return fam
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help_, tuple(labels), self._lock,
                              bounds=bounds)
                self._families[name] = fam
        return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._family(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = SECONDS_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, labels, bounds=buckets)

    def get(self, name: str) -> Optional[_Family]:
        return self._families.get(name)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def reset(self) -> None:
        """Drop every family (bench/tests isolate runs with this)."""
        with self._lock:
            self._families.clear()

    def sync_counters(self, prefix: str, mapping: Dict[str, Any],
                      help: str = "") -> None:
        """Mirror an external dict of cumulative counters (swap sdc
        counters, KV-tiering counters) into ``<prefix><key>_total``
        series via monotonic ``set_total``."""
        if not self.enabled:
            return
        for k, v in mapping.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.counter(f"{prefix}{k}_total", help).set_total(v)

    # -- export ----------------------------------------------------------

    def export_text(self) -> str:
        """Prometheus exposition format (text/plain; version 0.0.4)."""
        lines: List[str] = []
        for fam in self.families():
            lines.append(f"# HELP {fam.name} {fam.help or fam.name}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for child in fam.children():
                ls = child.labels
                if fam.kind in ("counter", "gauge"):
                    lines.append(
                        f"{fam.name}{_label_str(ls)} {_fmt(child.value())}")
                else:
                    counts, hsum, n = child.merged()
                    cum = 0
                    for i, ub in enumerate(child.bounds + (INF,)):
                        cum += counts[i]
                        bl = dict(ls)
                        bl["le"] = _fmt(ub)
                        lines.append(
                            f"{fam.name}_bucket{_label_str(bl)} {cum}")
                    lines.append(
                        f"{fam.name}_sum{_label_str(ls)} {_fmt(hsum)}")
                    lines.append(f"{fam.name}_count{_label_str(ls)} {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def export_json(self) -> Dict[str, Any]:
        """Self-describing document (flight-dump header, summarizer
        ``--metrics``/``--slo`` input).  Histograms carry raw bounds +
        per-bucket counts plus derived p50/p90/p99 so consumers need no
        quantile math of their own."""
        doc: Dict[str, Any] = {
            "record": "metrics", "version": _SCHEMA_VERSION,
            "unix_time": self.clock(),
            "counters": [], "gauges": [], "histograms": [],
        }
        for fam in self.families():
            for child in fam.children():
                base = {"name": fam.name, "help": fam.help,
                        "labels": dict(child.labels)}
                if fam.kind == "counter":
                    base["value"] = child.value()
                    doc["counters"].append(base)
                elif fam.kind == "gauge":
                    base["value"] = child.value()
                    doc["gauges"].append(base)
                else:
                    counts, hsum, n = child.merged()
                    base.update({
                        "buckets": list(child.bounds),
                        "counts": counts,            # per-bucket incl +Inf
                        "sum": hsum, "count": n,
                    })
                    for q in (50, 90, 99):
                        v = child.quantile(q)
                        base[f"p{q}"] = None if v is None else round(v, 6)
                    doc["histograms"].append(base)
        if self.slo is not None:
            try:
                doc["slo"] = self.slo.evaluate()
            except Exception:    # never let a bad objective kill a dump
                doc["slo"] = {}
        return doc

    def scalar_summary(self) -> Dict[str, float]:
        """Flat scalar view for ``serving_stages()["metrics"]`` /
        ``MonitorMaster`` (one level, scalar values only).  Keys are
        ``name{a=b}`` (+ ``_p50``.. for histograms)."""
        out: Dict[str, float] = {}
        for fam in self.families():
            for child in fam.children():
                key = fam.name + _label_str(child.labels)
                if fam.kind in ("counter", "gauge"):
                    out[key] = child.value()
                else:
                    _c, hsum, n = child.merged()
                    out[key + "_count"] = n
                    out[key + "_sum"] = round(hsum, 6)
                    for q in (50, 99):
                        v = child.quantile(q)
                        if v is not None:
                            out[key + f"_p{q}"] = round(v, 6)
        return out


def validate_metrics_doc(doc: Any) -> List[str]:
    """Structural checks on an ``export_json()`` document — shared by
    ``read_flight_record`` (embedded snapshots) and
    ``trace_summarize --validate``.  Returns a list of problems (empty
    == valid): envelope fields, per-series shapes, bucket-bound
    monotonicity, counts length == bounds + 1 (the +Inf bucket), and
    sum-of-counts == count."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["metrics doc is not an object"]
    if doc.get("record") != "metrics":
        problems.append(f"record != 'metrics' (got {doc.get('record')!r})")
    if not isinstance(doc.get("version"), int):
        problems.append("missing integer 'version'")
    for kind in ("counters", "gauges", "histograms"):
        seq = doc.get(kind)
        if not isinstance(seq, list):
            problems.append(f"'{kind}' is not a list")
            continue
        for i, m in enumerate(seq):
            where = f"{kind}[{i}]"
            if not isinstance(m, dict) or not isinstance(m.get("name"), str):
                problems.append(f"{where}: missing name")
                continue
            where = f"{kind}[{i}] ({m['name']})"
            if not isinstance(m.get("labels"), dict):
                problems.append(f"{where}: labels not a dict")
            if kind != "histograms":
                if not isinstance(m.get("value"), (int, float)):
                    problems.append(f"{where}: non-numeric value")
                continue
            bounds = m.get("buckets")
            counts = m.get("counts")
            if not isinstance(bounds, list) or not bounds:
                problems.append(f"{where}: missing buckets")
                continue
            if any(bounds[j] >= bounds[j + 1]
                   for j in range(len(bounds) - 1)):
                problems.append(f"{where}: bucket bounds not increasing")
            if not isinstance(counts, list) or \
                    len(counts) != len(bounds) + 1:
                problems.append(
                    f"{where}: counts length != len(buckets)+1")
                continue
            if any((not isinstance(c, int)) or c < 0 for c in counts):
                problems.append(f"{where}: negative/non-int bucket count")
            if sum(counts) != m.get("count"):
                problems.append(
                    f"{where}: sum(counts)={sum(counts)} != "
                    f"count={m.get('count')}")
    slo = doc.get("slo")
    if slo is not None and not isinstance(slo, dict):
        problems.append("'slo' is not an object")
    return problems


__all__.append("validate_metrics_doc")


# ---------------------------------------------------------------------------
# Process-wide singleton
# ---------------------------------------------------------------------------

def _env_on(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


metrics = MetricsRegistry(enabled=_env_on("DSTPU_METRICS", True))


def get_registry() -> MetricsRegistry:
    return metrics


def configure(**kw) -> MetricsRegistry:
    """Mutate the process singleton in place (importers hold references)."""
    return metrics.configure(**kw)
