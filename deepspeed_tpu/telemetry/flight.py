"""Flight recorder: postmortem timeline dumps on hard-failure paths.

The tracer's bounded per-thread rings double as a black box.  When a
hard failure fires (``CollectiveTimeout``, ``SwapCorruptionError``,
``KVRestoreError``, ``GradientAnomalyError``, SIGTERM preemption), the
raise site calls :func:`dump_on_fault` and the recent spans + events
land in a self-describing JSONL next to the emergency checkpoint — a
chaos kill leaves a timeline, not just counters.

File format (one JSON object per line):

    {"record": "flight", "version": 1, "reason": ..., "exception":
     {"type": ..., "message": ...}, "pid": ..., "host": ..., ...}
    {"ph": "X", "name": "swap_in_wait", "ts": ..., "dur": ..., ...}
    ...
    {"record": "end", "events": N}

The trailing ``end`` line carries the event count, so a truncated dump
(process killed mid-write) is detectable: ``chaos_train`` exits
nonzero when the end line is missing or the count disagrees.

Dump location: explicit ``dir`` argument > ``DSTPU_FLIGHT_DIR`` env >
``<tempdir>/dstpu_flight``.  Dumps NEVER raise — a broken disk on a
failure path must not mask the original fault.
"""
from __future__ import annotations

import itertools
import json
import os
import re
import socket
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.tracer import trace
from deepspeed_tpu.telemetry.metrics import metrics as _metrics

__all__ = ["dump_on_fault", "flight_dir", "last_dump_path",
           "read_flight_record"]

_SCHEMA_VERSION = 1
_seq = itertools.count()
_last_dump: Optional[str] = None
_DUMPED_ATTR = "_dstpu_flight_dump"


def flight_dir(dir: Optional[str] = None) -> str:
    """Resolve the dump directory (arg > env > tempdir fallback)."""
    return (dir or os.environ.get("DSTPU_FLIGHT_DIR")
            or os.path.join(tempfile.gettempdir(), "dstpu_flight"))


def last_dump_path() -> Optional[str]:
    """Path of the most recent dump this process wrote (tests/chaos)."""
    return _last_dump


def dump_on_fault(reason: str, exc: Optional[BaseException] = None,
                  dir: Optional[str] = None,
                  extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump the flight-recorder ring; returns the path or None.

    Re-dumping the SAME exception instance into the SAME directory is
    suppressed (a fault that unwinds through several handlers — raise
    site, engine handler — writes once per destination, so the engine
    can still place a copy next to the emergency checkpoint by passing
    an explicit ``dir``).
    """
    global _last_dump
    try:
        out_dir = flight_dir(dir)
        if exc is not None:
            dumped = getattr(exc, _DUMPED_ATTR, None)
            if dumped is not None and out_dir in dumped:
                return dumped[out_dir]
        os.makedirs(out_dir, exist_ok=True)
        tag = re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:64] or "fault"
        path = os.path.join(
            out_dir, f"flight_{tag}_{os.getpid()}_{next(_seq)}.jsonl")
        events = trace.snapshot()
        header = {
            "record": "flight", "version": _SCHEMA_VERSION,
            "reason": reason, "pid": os.getpid(),
            "host": socket.gethostname(), "unix_time": time.time(),
            "clock": "perf_counter_us_since_tracer_epoch",
            "events": len(events),
            "exception": (None if exc is None else
                          {"type": type(exc).__name__,
                           "message": str(exc)[:2000]}),
        }
        if extra:
            header["extra"] = extra
        try:
            # cumulative counters + SLO state ride along with the span
            # ring, so a postmortem has the "how long has this been
            # going on" axis, not just the last few seconds
            if _metrics.enabled:
                header["metrics"] = _metrics.export_json()
        except Exception:
            pass                # metrics must never break a fault dump
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(header) + "\n")
            for ev in events:
                f.write(json.dumps(ev) + "\n")
            f.write(json.dumps({"record": "end",
                                "events": len(events)}) + "\n")
        if exc is not None:
            dumped = getattr(exc, _DUMPED_ATTR, None) or {}
            dumped[out_dir] = path
            try:
                setattr(exc, _DUMPED_ATTR, dumped)
            except Exception:
                pass            # exceptions with __slots__: re-dump is fine
        _last_dump = path
        return path
    except Exception:
        return None             # never mask the original fault


def read_flight_record(path: str) -> Tuple[Dict[str, Any],
                                           List[Dict[str, Any]]]:
    """Parse + validate a dump; raises ``ValueError`` on a malformed or
    truncated file.  Returns ``(header, events)``."""
    with open(path, "r", encoding="utf-8") as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty flight record")
    header = json.loads(lines[0])
    if header.get("record") != "flight":
        raise ValueError(f"{path}: missing flight header")
    tail = json.loads(lines[-1])
    if tail.get("record") != "end":
        raise ValueError(f"{path}: truncated (no end line)")
    events = [json.loads(ln) for ln in lines[1:-1]]
    if tail.get("events") != len(events) or header.get(
            "events") != len(events):
        raise ValueError(
            f"{path}: event count mismatch (header={header.get('events')} "
            f"end={tail.get('events')} actual={len(events)})")
    snap = header.get("metrics")
    if snap is not None:
        from deepspeed_tpu.telemetry.metrics import validate_metrics_doc
        problems = validate_metrics_doc(snap)
        if problems:
            raise ValueError(
                f"{path}: bad embedded metrics snapshot: "
                + "; ".join(problems[:5]))
    return header, events
