"""Silent-data-corruption defense: checksums for the offload hot path.

At fleet scale, flaky cores, DRAM and storage corrupt data without any
error surfacing ("Cores that don't count", Hochschild et al., HotOS'21).
The NVMe moment stream (``runtime/swap_tensor.py``) moves every Adam
moment byte disk->host->device and back each step, so a single flipped
bit silently poisons training unless the stream is tamper-evident.
This module provides the digest primitives the swapper stores in its
metadata and re-checks on every swap-in; the verification POLICY
(re-read retry, quarantine, :class:`~deepspeed_tpu.resilience.guards.
SwapCorruptionError` escalation) lives with the swapper.

The default algorithm is chosen for throughput, not cryptography — the
threat is bit flips, not an adversary.  All three detect any single
flipped bit (and any single corrupted word/byte) in a buffer:

``sum64``     wraparound sum of the buffer's ``uint64`` words,
              numpy-vectorized (measured ~9 GB/s/core — several times
              the moment stream it guards, so verification hides behind
              the pipeline's existing latency budget).  Weakest against
              multi-word corruption (two flips can cancel).
``adler32``   ``zlib.adler32`` (~2.6 GB/s/core); detects all single-byte
              changes, weak on very short buffers (not a concern at
              bucket granularity).
``crc32``     ``zlib.crc32`` (~1.1 GB/s/core); strongest — all burst
              errors up to 32 bits — and the same algorithm the
              checkpoint manifests use.

Digests are stored as ``(value, nbytes)`` so truncation is detected
even when a short read happens to checksum clean.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["CHECKSUM_ALGOS", "checksum", "digest", "DigestPool"]

CHECKSUM_ALGOS = ("sum64", "adler32", "crc32")

_U64 = (1 << 64) - 1


def _sum64(v: np.ndarray) -> int:
    """Wraparound sum over uint64 words (+ trailing bytes + the
    length, so buffers of zeros of different sizes don't collide).
    A flipped bit changes exactly one word by a nonzero power of two,
    which the mod-2^64 sum always reflects."""
    n8 = v.size & ~np.intp(7)
    s = int(np.add.reduce(v[:n8].view(np.uint64))) & _U64 if n8 else 0
    for b in v[n8:]:                       # tail (len % 8 bytes)
        s = (s + int(b)) & _U64
    return (s + v.size) & _U64


def checksum(buf: np.ndarray, algo: str = "sum64") -> int:
    """Digest of a C-contiguous numpy buffer under ``algo``."""
    v = np.ascontiguousarray(buf).reshape(-1).view(np.uint8)
    if algo == "sum64":
        return _sum64(v)
    import zlib

    if algo == "adler32":
        return zlib.adler32(memoryview(v))
    if algo == "crc32":
        return zlib.crc32(memoryview(v))
    raise ValueError(
        f"unknown checksum algo {algo!r} (choose from {CHECKSUM_ALGOS})")


def digest(buf: np.ndarray, algo: str = "sum64") -> Tuple[int, int]:
    """``(checksum, nbytes)`` — the unit stored in swapper metadata."""
    return checksum(buf, algo), int(buf.nbytes)


class DigestPool:
    """Side-thread digest jobs on the shared bounded-async-stage
    substrate (:mod:`deepspeed_tpu.utils.async_stage`).

    The write-side digest pattern every verified stream shares (NVMe
    moment stream, tiered KV spill): the submitted buffer is immutable
    until its IO is reaped, so the digest job races nothing and the
    checksum genuinely overlaps the in-flight IO — numpy/zlib release
    the GIL.  Keyed ``submit`` + selective ``pop`` let a read-side
    verify gate join exactly ITS digest without blocking on unrelated
    in-flight writes; ``settle()`` is the forced-drain point the
    save/spill/restore paths use when they need the full picture.

    Below ``defer_min`` bytes a thread-pool round trip costs more than
    the digest itself (sum64 runs ~9 GB/s/core), so small buffers
    digest inline — ``note`` makes that call so call sites don't.
    ``spun`` reports whether the lazy executor ever started (a
    verify-off stream must never pay for one).
    """

    def __init__(self, algo: str = "sum64", workers: int = 2,
                 defer_min: int = 4 << 20, depth: int = 256,
                 timers: Optional[Any] = None,
                 thread_name_prefix: str = "dstpu-sdc") -> None:
        from deepspeed_tpu.utils.async_stage import (BoundedAsyncStage,
                                                     StageTimers)

        self.algo = algo
        self.defer_min = int(defer_min)
        self._workers = max(1, int(workers))
        self._prefix = thread_name_prefix
        self._exec = None                       # lazy ThreadPoolExecutor
        self.timers = timers if timers is not None else StageTimers()
        self._stage = BoundedAsyncStage(
            waiter=lambda fut: fut.result(), depth=depth,
            timers=self.timers, name="sdc-digest")

    @property
    def spun(self) -> bool:
        return self._exec is not None

    @property
    def in_flight(self) -> int:
        return self._stage.in_flight

    def __contains__(self, key: Any) -> bool:
        return key in self._stage

    def digest(self, buf: np.ndarray) -> Tuple[int, int]:
        return digest(buf, self.algo)

    def submit(self, key: Any, fn: Callable[[], Any]) -> None:
        """Defer ``fn`` (a digest computation over buffers that stay
        immutable until joined) to the side pool under ``key``."""
        if self._exec is None:
            from concurrent.futures import ThreadPoolExecutor

            self._exec = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix=self._prefix)
        self._stage.submit(key, self._exec.submit(fn))

    def note(self, key: Any, buf: np.ndarray,
             defer: bool = True) -> Optional[Tuple[int, int]]:
        """Digest ``buf`` under ``key``: deferred to the side pool when
        worthwhile (returns None — fetch via ``pop``/``settle``), else
        inline (returns the digest immediately)."""
        if defer and buf.nbytes >= self.defer_min:
            self.submit(key, lambda: self.digest(buf))
            return None
        return self.digest(buf)

    def pop(self, key: Any, default: Any = None) -> Any:
        """Selective join of one keyed job (None/default when absent)."""
        return self._stage.pop(key, default)

    def settle(self) -> Dict[Any, Any]:
        """Forced drain: join every in-flight job, keyed results out."""
        out = {}
        for key in self._stage.keys():
            out[key] = self._stage.pop(key)
        return out

    def discard(self, key: Any) -> None:
        """Join-and-forget one job (invalidation: its bytes changed)."""
        self._stage.pop(key, None)

    def clear(self) -> None:
        """Invalidation hook: join-and-forget everything in flight."""
        for key in self._stage.keys():
            self._stage.pop(key, None)

    def close(self) -> None:
        self.clear()
        if self._exec is not None:
            self._exec.shutdown(wait=True)
            self._exec = None
