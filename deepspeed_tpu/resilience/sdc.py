"""Silent-data-corruption defense: checksums for the offload hot path.

At fleet scale, flaky cores, DRAM and storage corrupt data without any
error surfacing ("Cores that don't count", Hochschild et al., HotOS'21).
The NVMe moment stream (``runtime/swap_tensor.py``) moves every Adam
moment byte disk->host->device and back each step, so a single flipped
bit silently poisons training unless the stream is tamper-evident.
This module provides the digest primitives the swapper stores in its
metadata and re-checks on every swap-in; the verification POLICY
(re-read retry, quarantine, :class:`~deepspeed_tpu.resilience.guards.
SwapCorruptionError` escalation) lives with the swapper.

The default algorithm is chosen for throughput, not cryptography — the
threat is bit flips, not an adversary.  All three detect any single
flipped bit (and any single corrupted word/byte) in a buffer:

``sum64``     wraparound sum of the buffer's ``uint64`` words,
              numpy-vectorized (measured ~9 GB/s/core — several times
              the moment stream it guards, so verification hides behind
              the pipeline's existing latency budget).  Weakest against
              multi-word corruption (two flips can cancel).
``adler32``   ``zlib.adler32`` (~2.6 GB/s/core); detects all single-byte
              changes, weak on very short buffers (not a concern at
              bucket granularity).
``crc32``     ``zlib.crc32`` (~1.1 GB/s/core); strongest — all burst
              errors up to 32 bits — and the same algorithm the
              checkpoint manifests use.

Digests are stored as ``(value, nbytes)`` so truncation is detected
even when a short read happens to checksum clean.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = ["CHECKSUM_ALGOS", "checksum", "digest"]

CHECKSUM_ALGOS = ("sum64", "adler32", "crc32")

_U64 = (1 << 64) - 1


def _sum64(v: np.ndarray) -> int:
    """Wraparound sum over uint64 words (+ trailing bytes + the
    length, so buffers of zeros of different sizes don't collide).
    A flipped bit changes exactly one word by a nonzero power of two,
    which the mod-2^64 sum always reflects."""
    n8 = v.size & ~np.intp(7)
    s = int(np.add.reduce(v[:n8].view(np.uint64))) & _U64 if n8 else 0
    for b in v[n8:]:                       # tail (len % 8 bytes)
        s = (s + int(b)) & _U64
    return (s + v.size) & _U64


def checksum(buf: np.ndarray, algo: str = "sum64") -> int:
    """Digest of a C-contiguous numpy buffer under ``algo``."""
    v = np.ascontiguousarray(buf).reshape(-1).view(np.uint8)
    if algo == "sum64":
        return _sum64(v)
    import zlib

    if algo == "adler32":
        return zlib.adler32(memoryview(v))
    if algo == "crc32":
        return zlib.crc32(memoryview(v))
    raise ValueError(
        f"unknown checksum algo {algo!r} (choose from {CHECKSUM_ALGOS})")


def digest(buf: np.ndarray, algo: str = "sum64") -> Tuple[int, int]:
    """``(checksum, nbytes)`` — the unit stored in swapper metadata."""
    return checksum(buf, algo), int(buf.nbytes)
