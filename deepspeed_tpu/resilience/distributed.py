"""Distributed-health layer: cross-process failure detection.

The comm-level half of the resilience subsystem (the reference
DeepSpeed treats communication as a first-class failure domain — its
compressed collectives tolerate lossy links and its elastic agent
assumes ranks die mid-collective).  Four pieces:

- :class:`CollectiveTimeout` — raised by the collective watchdog
  (``comm/watchdog.py``) when an eager collective exceeds its deadline
  instead of hanging until an outer harness timeout.  The engine routes
  it through the preemption path (emergency checkpoint attempt, then a
  clean nonzero abort) and the elastic agent treats it as a hard
  failure that consumes a restart.
- :class:`DesyncDetector` — periodic cross-rank comparison of values
  that MUST be replica-identical under SPMD (loss, grad norm, local
  views of collective results).  A corrupted collective that broke the
  replication invariant becomes a loud
  :class:`~deepspeed_tpu.resilience.guards.GradientAnomalyError`
  instead of silent divergence.
- :func:`build_straggler_report` — names the straggler rank from
  cross-rank per-op collective timings (the rank everyone waits for
  arrives last and therefore WAITS LEAST; argmin of mean latency).
  ``comm.log_summary(show_straggler=True)`` aggregates and renders it.
- :func:`install_injector_from_env` — plumbs a
  :class:`~deepspeed_tpu.resilience.faults.FaultInjector` spec through
  environment variables into subprocess workers (the multiproc chaos
  tests and real chaos drills inject per-rank comm faults this way).

This module must not import ``deepspeed_tpu.comm`` at module scope —
the comm facade's watchdog imports :class:`CollectiveTimeout` from
here.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.resilience.faults import FaultInjector
from deepspeed_tpu.resilience.guards import GradientAnomalyError
from deepspeed_tpu.utils.logging import logger

__all__ = ["CollectiveTimeout", "DesyncDetector", "build_straggler_report",
           "install_injector_from_env", "tree_checksum", "allgather_json"]


class CollectiveTimeout(RuntimeError):
    """An eager collective (or cross-process barrier) exceeded the
    watchdog deadline — a peer dropped the collective, died
    mid-collective, or the transport wedged.  Fail fast: the process
    must abort (after an emergency-checkpoint attempt) rather than
    hang until an outer harness kills it."""


# ---------------------------------------------------------------------------
# Cross-process exchange primitive
# ---------------------------------------------------------------------------

_JSON_PAD = 8192


def allgather_json(obj: Any, pad: int = _JSON_PAD) -> List[Any]:
    """Gather one small JSON-serializable object per process.

    Content length may differ per rank (``process_allgather`` needs
    identical shapes), so payloads are padded to ``pad`` bytes.
    Single-process: returns ``[obj]`` without touching the transport.
    """
    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    raw = json.dumps(obj).encode()
    assert len(raw) <= pad, f"allgather_json payload {len(raw)}B > {pad}B"
    buf = np.zeros(pad, np.uint8)
    buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    out = []
    for row in gathered.reshape(jax.process_count(), pad):
        data = row.tobytes().rstrip(b"\x00")
        out.append(json.loads(data.decode()))
    return out


def tree_checksum(tree: Any) -> float:
    """Cheap checksum of THIS process's local view of a pytree: the sum
    over every leaf's addressable shards.  Two processes holding what
    should be identical replicas get identical checksums; a corrupted
    collective that delivered different data to one rank's shards
    shows up as a mismatch."""
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            for sh in leaf.addressable_shards:
                total += float(np.sum(np.asarray(sh.data, np.float64)))
        else:
            total += float(np.sum(np.asarray(leaf, np.float64)))
    return total


# ---------------------------------------------------------------------------
# Desync detection
# ---------------------------------------------------------------------------


class DesyncDetector:
    """Periodic cross-rank comparison of replica-identical scalars.

    Under single-controller SPMD every *global* computation is
    consistent by construction; what CAN silently diverge is per-rank
    local state — the local replica of a collective result a lossy
    link corrupted, host-side optimizer streams, fetched metrics.
    ``check`` exchanges named local scalars across processes and raises
    :class:`GradientAnomalyError` when any of them disagree beyond
    ``tolerance`` — turning a corrupted collective into a loud abort
    (the engine's ``SkippedStepGuard`` story extended across ranks).

    Off by default; the engine builds one when
    ``resilience.comm.desync_interval > 0`` and feeds it the loss /
    grad-norm scalars it already fetches.  Single-process ``check`` is
    a no-op that still counts (the code path stays exercised).
    """

    def __init__(self, interval: int, tolerance: float = 0.0):
        assert interval > 0, "use interval > 0 (0 means: no detector)"
        self.interval = int(interval)
        self.tolerance = float(tolerance)
        self.checks = 0
        self.mismatches = 0

    def should_check(self, step: int) -> bool:
        return step > 0 and step % self.interval == 0

    def check(self, values: Dict[str, float], step: int) -> bool:
        """Cross-check ``{name: local_scalar}``; raises on divergence."""
        self.checks += 1
        rank = jax.process_index()
        per_rank = allgather_json({"rank": rank, "values": values})
        bad = []
        for name in values:
            vals = [float(r["values"][name]) for r in per_rank]
            good = [v for v in vals if np.isfinite(v)]
            spread = (max(good) - min(good)) if good else float("inf")
            if len(good) < len(vals) or spread > self.tolerance:
                bad.append((name, vals))
        if not bad:
            return True
        self.mismatches += 1
        detail = "; ".join(
            f"{name}: " + ", ".join(f"rank{i}={v:.6g}"
                                    for i, v in enumerate(vals))
            for name, vals in bad)
        err = GradientAnomalyError(
            f"cross-rank desync at step {step}: {detail} — ranks hold "
            "different values for replica-identical state (a corrupted "
            "collective or diverged host-side stream). Abort and resume "
            "from the last verified checkpoint "
            "(resilience.comm.desync_interval controls this check).")
        from deepspeed_tpu.telemetry import flight

        flight.dump_on_fault("cross_rank_desync", err,
                             extra={"step": int(step), "rank": int(rank)})
        raise err


# ---------------------------------------------------------------------------
# Straggler telemetry
# ---------------------------------------------------------------------------


def build_straggler_report(per_rank: List[Dict[str, Any]],
                           min_spread_s: float = 0.020,
                           min_ratio: float = 2.0) -> Dict[str, Dict]:
    """Name the straggler per op from cross-rank mean latencies.

    ``per_rank[r]`` maps ``op -> {"mean_s": float, "count": int}`` for
    rank ``r``.  The straggler is the rank with the SMALLEST mean wait:
    it arrives last, so every peer's timing includes waiting for it
    while its own collective completes immediately.  An op is only
    flagged when the max/min spread clears both an absolute floor
    (``min_spread_s``) and a ratio (``min_ratio``) — uniform jitter
    must not produce accusations."""
    ops = sorted({op for r in per_rank for op in r})
    report: Dict[str, Dict] = {}
    for op in ops:
        means = [float(r[op]["mean_s"]) if op in r else float("nan")
                 for r in per_rank]
        known = [(i, m) for i, m in enumerate(means) if np.isfinite(m)]
        if len(known) < 2:
            continue
        lo_rank, lo = min(known, key=lambda t: t[1])
        hi_rank, hi = max(known, key=lambda t: t[1])
        spread = hi - lo
        flagged = (spread >= min_spread_s
                   and hi >= min_ratio * max(lo, 1e-9))
        report[op] = {
            "straggler_rank": lo_rank if flagged else None,
            "spread_ms": round(spread * 1e3, 3),
            "min_ms": round(lo * 1e3, 3),
            "max_ms": round(hi * 1e3, 3),
            "slowest_peer_rank": hi_rank,
            "per_rank_ms": [round(m * 1e3, 3) for m in means],
        }
    return report


# ---------------------------------------------------------------------------
# Worker-side fault plumbing
# ---------------------------------------------------------------------------


def install_injector_from_env(env: Optional[Dict[str, str]] = None
                              ) -> Optional[FaultInjector]:
    """Arm a :class:`FaultInjector` in THIS process from the
    environment — the path test harnesses and chaos drills use to
    inject per-rank comm faults into subprocess workers.

    ``DSTPU_FAULT_SPEC``
        the :meth:`FaultInjector.from_spec` wire format; absent = no-op.
    ``DSTPU_FAULT_RANK``
        only arm when ``jax.process_index()`` matches (per-rank faults:
        "corrupt the payload on ONE rank"); absent = every rank.
    ``DSTPU_FAULT_SEED``
        injector seed (default 0).

    The injector is ENTERED (installed as the process-global active
    injector); callers that need to disarm mid-process hold the return
    value and call ``__exit__``.  Call after ``jax.distributed``
    initialization so the rank gate sees the real process index."""
    env = os.environ if env is None else env
    spec = env.get("DSTPU_FAULT_SPEC")
    if not spec:
        return None
    rank_gate = env.get("DSTPU_FAULT_RANK")
    if rank_gate is not None and jax.process_index() != int(rank_gate):
        return None
    inj = FaultInjector.from_spec(spec, seed=int(env.get("DSTPU_FAULT_SEED",
                                                         "0")))
    inj.__enter__()
    logger.warning(f"fault injector armed from DSTPU_FAULT_SPEC on rank "
                   f"{jax.process_index()}: {spec!r}")
    return inj
