"""Fault-tolerance layer: hardened checkpoint I/O helpers, retry/backoff,
training guards, the deterministic fault-injection harness, and the
distributed-health layer (collective watchdog exception, desync
detection, straggler aggregation).

Wired through ``checkpoint/`` (staged atomic commits, crc32-verified
manifests, quarantine + fallback on load), ``comm/`` (eager-collective
fault sites + the collective watchdog, ``comm/watchdog.py``),
``runtime/engine.py`` (preemption hook, gradient-anomaly guard, desync
check, collective-timeout routing), and ``launcher/elastic_agent.py``
(restart budget with exponential backoff; collective timeouts consume
restarts).  Config knobs live in the ``resilience`` block of the
DeepSpeed config (``config/config.py ResilienceConfig`` and its
``resilience.comm`` subtree).
"""
from deepspeed_tpu.resilience.distributed import (CollectiveTimeout,
                                                  DesyncDetector,
                                                  build_straggler_report,
                                                  install_injector_from_env,
                                                  tree_checksum)
from deepspeed_tpu.resilience.faults import (FaultInjector, SimulatedCrash,
                                             flip_bit_in_file,
                                             torn_write_file)
from deepspeed_tpu.resilience.guards import (GradientAnomalyError,
                                             SkippedStepGuard,
                                             SwapCorruptionError)
from deepspeed_tpu.resilience.retry import (backoff_delays,
                                            call_with_retries, retriable)
from deepspeed_tpu.resilience.sdc import CHECKSUM_ALGOS

__all__ = ["FaultInjector", "SimulatedCrash", "torn_write_file",
           "flip_bit_in_file",
           "GradientAnomalyError", "SkippedStepGuard",
           "SwapCorruptionError", "CHECKSUM_ALGOS",
           "backoff_delays", "call_with_retries", "retriable",
           "CollectiveTimeout", "DesyncDetector", "build_straggler_report",
           "install_injector_from_env", "tree_checksum"]
