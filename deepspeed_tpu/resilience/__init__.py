"""Fault-tolerance layer: hardened checkpoint I/O helpers, retry/backoff,
training guards, and the deterministic fault-injection harness.

Wired through ``checkpoint/`` (staged atomic commits, crc32-verified
manifests, quarantine + fallback on load), ``runtime/engine.py``
(preemption hook, gradient-anomaly guard), and
``launcher/elastic_agent.py`` (restart budget with exponential
backoff).  Config knobs live in the ``resilience`` block of the
DeepSpeed config (``config/config.py ResilienceConfig``).
"""
from deepspeed_tpu.resilience.faults import (FaultInjector, SimulatedCrash,
                                             torn_write_file)
from deepspeed_tpu.resilience.guards import (GradientAnomalyError,
                                             SkippedStepGuard)
from deepspeed_tpu.resilience.retry import (backoff_delays,
                                            call_with_retries, retriable)

__all__ = ["FaultInjector", "SimulatedCrash", "torn_write_file",
           "GradientAnomalyError", "SkippedStepGuard",
           "backoff_delays", "call_with_retries", "retriable"]
