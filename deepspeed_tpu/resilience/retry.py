"""Jittered exponential-backoff retry for transient I/O failures.

The reusable half of the fault-tolerance layer (resilience/): checkpoint
blob/index writes, the NVMe moment-file swap path, and the elastic
agent's restart loop all share this one backoff policy instead of each
growing an ad-hoc ``time.sleep`` loop.

Determinism for tests: the wait primitive is the module-level ``_sleep``
(monkeypatch it with a fake clock — no resilience test may really
sleep), and the jitter draws from an injectable ``random.Random``.
"""
from __future__ import annotations

import functools
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from deepspeed_tpu.utils.logging import logger

# the injectable clock: tests replace this with a recording fake so
# backoff paths stay tier-1-fast while still exercising real delays
_sleep = time.sleep


def backoff_delays(attempts: int, base_s: float, cap_s: float = 30.0,
                   jitter: float = 0.5,
                   rng: Optional[random.Random] = None
                   ) -> Iterator[float]:
    """The ``attempts - 1`` delays between ``attempts`` tries:
    ``min(cap, base * 2**i) * (1 + jitter * u)``, ``u ~ U[0, 1)``.

    Jitter is additive-only (delays never shrink below the exponential
    floor) so a fleet of restarting workers decorrelates without any
    of them retrying early."""
    rng = rng or random.Random()
    for i in range(max(attempts - 1, 0)):
        yield min(cap_s, base_s * (2.0 ** i)) * (1.0 + jitter * rng.random())


def retriable(attempts: int = 4, base_s: float = 0.05, cap_s: float = 2.0,
              retry_on: Tuple[Type[BaseException], ...] = (OSError,),
              jitter: float = 0.5, rng: Optional[random.Random] = None,
              sleep: Optional[Callable[[float], None]] = None):
    """Decorator: retry ``fn`` on ``retry_on`` with jittered exponential
    backoff, re-raising the last failure once ``attempts`` is spent.

    The decorated function must be idempotent under partial completion
    (checkpoint writers qualify: every retry rewrites the staged file
    from the start)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            delays = backoff_delays(attempts, base_s, cap_s, jitter, rng)
            attempt = 1
            while True:
                try:
                    return fn(*args, **kwargs)
                except retry_on as e:
                    delay = next(delays, None)
                    if delay is None:
                        raise              # budget spent: re-raise e
                    logger.warning(
                        f"{fn.__qualname__}: transient failure "
                        f"(attempt {attempt}/{attempts}): {e!r}; "
                        f"retrying in {delay:.2f}s")
                    (sleep or _sleep)(delay)
                    attempt += 1
        return wrapper
    return deco


def call_with_retries(fn: Callable, *args, **retry_kw):
    """One-off form of :func:`retriable` for call sites that can't be
    decorated (e.g. wrapping ``shutil.copy2``)."""
    return retriable(**retry_kw)(fn)(*args)
