"""Runtime training guards.

The engine-side half of the fault-tolerance layer: detectors that turn
"silently wrong forever" failure modes into loud, actionable aborts.
"""
from __future__ import annotations

from deepspeed_tpu.utils.logging import logger


class GradientAnomalyError(RuntimeError):
    """Training aborted because every recent step produced non-finite
    gradients — the run is spinning the loss scaler, not learning."""


class SwapCorruptionError(RuntimeError):
    """Silent data corruption detected in the NVMe offload hot path:
    a swapped moment buffer failed checksum verification and the
    blocking re-read retries could not produce clean bytes (the
    corruption is on the media, not transient host-buffer/DMA noise).
    The offending swap file is quarantined before this raises; the
    engine routes it through the preemption/emergency-checkpoint path
    so the elastic agent restarts from the last verified checkpoint
    instead of training on garbage."""


class SkippedStepGuard:
    """Counts CONSECUTIVE overflow-skipped steps and aborts past a bound.

    The fp16 dynamic loss scaler recovers from isolated overflows by
    halving the scale; what it cannot recover from is a genuinely
    divergent model (NaN weights, poisoned data), where it halves the
    scale forever while every step is skipped.  The reference engine
    trains on silently in that state — this guard raises
    :class:`GradientAnomalyError` after ``bound`` consecutive skips
    (``resilience.max_consecutive_skips``; 0 disables)."""

    def __init__(self, bound: int):
        assert bound > 0, "use bound > 0 (0 means: do not build the guard)"
        self.bound = int(bound)
        self.consecutive = 0

    def update(self, overflowed: bool, step: int) -> None:
        if overflowed:
            from deepspeed_tpu.telemetry.metrics import metrics as _metrics
            if _metrics.enabled:
                _metrics.counter(
                    "dstpu_skipped_steps_total",
                    "Optimizer steps skipped on gradient overflow").inc()
        if not overflowed:
            if self.consecutive:
                logger.info(f"step {step}: finite gradients after "
                            f"{self.consecutive} consecutive skips")
            self.consecutive = 0
            return
        self.consecutive += 1
        if self.consecutive >= self.bound:
            err = GradientAnomalyError(
                f"{self.consecutive} consecutive steps produced non-finite "
                f"gradients (through step {step}); the loss scaler cannot "
                "recover from a divergent model. Inspect the data/loss and "
                "resume from the last verified checkpoint "
                "(resilience.max_consecutive_skips bounds this abort).")
            from deepspeed_tpu.telemetry import flight
            from deepspeed_tpu.telemetry.metrics import metrics as _metrics

            if _metrics.enabled:
                _metrics.counter(
                    "dstpu_gradient_anomalies_total",
                    "Aborts on consecutive non-finite gradients").inc()
            flight.dump_on_fault("gradient_anomaly", err,
                                 extra={"step": int(step),
                                        "consecutive": self.consecutive})
            raise err
