"""Deterministic fault injection.

The same seeded injector drives the unit tests under
``tests/unit/checkpoint/test_resilience.py`` and the
``scripts/chaos_train.py`` soak: production code is instrumented with
cheap :func:`hook` calls (no-ops when no injector is active), and an
active :class:`FaultInjector` turns specific hook firings into torn
writes, transient ``OSError`` s, simulated process death, or SIGTERM
delivery — reproducibly, keyed only on the per-site call count and the
injector's seed.

Instrumented sites (the stable surface; grep for ``faults.hook``):

========================  ==================================================
``ckpt.write_blob``       once per blob-write attempt (retry target)
``ckpt.write_record``     before each record buffer is written (torn/crash)
``ckpt.write_index``      before the manifest JSON is written
``ckpt.commit``           just before the atomic staging->tag rename
``ckpt.read_record``      before each shard-record read (retry target)
``swap.write_item``       before each NVMe moment-file write
``swap.write_bucket``     before each pipelined bucket write-back submit
                          (async submit AND its blocking retry path)
``swap.read_bucket``      after each pipelined bucket read completes,
                          before its checksum verification (fires again
                          per blocking re-read — transient vs persistent
                          corruption is modeled by ``count``)
``swap.read_item``        after each leafwise moment-shard read joins,
                          before verification (and per re-read)
``kv.read_page``          per spilled-KV page per restore attempt
                          (inference/kv_tiering.py), before the page's
                          digest check — fires again per re-read, so
                          ``count`` models transient (heals) vs
                          persistent (quarantine + re-prefill) flips
``kv.write``              per tiered-KV NVMe write submit (spill
                          write-back AND the degraded-mode recovery
                          probe) — ``io_error`` here models a failing
                          device; ``count`` spans the probe window so
                          the tier stays offline until the device heals
``handoff.import``        once per session at the decode-role
                          replica's handoff import
                          (inference/v2/ragged_engine.py
                          ``import_handoff``), before the payload is
                          installed — ``bitflip`` corrupts the wire
                          payload (the donor's digests then fail the
                          restore: re-read, quarantine, fold to
                          re-prefill), ``io_error``/``crash`` kill the
                          import op (replica-death path)
``router.dispatch``       once per router->replica dispatch
                          (serving/router.py ``_send``) — ``io_error``
                          kills the dispatch (replica-death path),
                          ``slow`` delays it
``replica.step``          once per engine step op ON the replica thread
                          (serving/replica_set.py) — ``crash``/
                          ``io_error`` is a replica dying mid-decode
``replica.hang``          alongside ``replica.step`` — honors ``hang``
                          /``slow`` directives by sleeping ``param``
                          seconds on the replica thread (a wedged
                          decode; the serving watchdog's quarry)
``http.flush``            before each SSE token-event flush
                          (serving/server.py) — ``io_error`` breaks the
                          client socket mid-stream (cancel must
                          propagate), ``slow`` delays the flush
``comm.all_reduce``       once per EAGER all_reduce call (comm/comm.py)
``comm.all_gather``       once per eager all_gather call
``comm.broadcast``        once per eager broadcast call
``comm.barrier``          once per ``comm.barrier()`` call
``comm.reduce_scatter``   once per eager reduce_scatter call
``comm.all_to_all``       once per eager all_to_all call
``comm.ppermute``         once per eager ppermute call
========================  ==================================================

Fault kinds:

``oserror``   raise a transient ``OSError`` (retry/backoff target)
``torn``      write only ``param`` fraction of the bytes, then die
``crash``     raise :class:`SimulatedCrash` (process death mid-op)
``sigterm``   deliver a real SIGTERM (preemption-handler target)
``corrupt``   comm sites: scale ``param`` fraction of this rank's LOCAL
              view of the collective result (a lossy link delivering
              corrupted data to one receiver — breaks cross-rank
              replication, the desync detector's quarry)
``straggle``  comm sites: sleep ``param`` seconds before joining the
              collective (a slow rank; peers stall waiting for it)
``drop``      comm sites: skip the collective entirely on this rank,
              so peers hang in it (the collective-watchdog's quarry)
``bitflip``   swap/kv read sites: flip ``param`` random bit(s) of the
              just-read buffer (silent host-buffer/DMA/media
              corruption — the SDC verifier's quarry).  Positions come
              from the injector's seeded rng; with ``count=1`` the
              corruption is transient (the re-read heals), a large
              ``count`` or :func:`flip_bit_in_file` models persistent
              on-media corruption
``io_error``  raise ``OSError(EIO)`` — a HARD device error
              (vs ``oserror``'s transient): the degraded-mode tiering
              trip counter and the serving death paths key on it
``hang``      serving sites: sleep ``param`` seconds at the site (a
              wedged op — finite so tests terminate, but longer than
              any watchdog deadline under test)
``slow``      serving sites: sleep ``param`` seconds (a straggling
              replica/socket — the hedging threshold's quarry, below
              the watchdog deadline)

A fault is scheduled with ``inject(site, kind, ...)`` (or the named
helpers); ``after`` skips that many firings first and ``count`` bounds
how many firings trigger.  Only one injector may be active per process
(they install into a module global — the hooks must stay free when
disarmed).  For subprocess workers, :func:`FaultInjector.from_spec`
parses the ``DSTPU_FAULT_SPEC`` wire format (see
``resilience/distributed.py install_injector_from_env``).
"""
from __future__ import annotations

import random
import signal as _signal
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["FaultInjector", "SimulatedCrash", "hook", "active",
           "torn_write_file", "apply_bitflip", "flip_bit_in_file"]


class SimulatedCrash(BaseException):
    """Emulates process death mid-operation.  Derives from
    ``BaseException`` so ordinary ``except Exception`` recovery/retry
    paths cannot swallow it — a real SIGKILL would not run them
    either."""


class _Fault:
    __slots__ = ("site", "kind", "count", "after", "param")

    def __init__(self, site: str, kind: str, count: int, after: int,
                 param: float):
        self.site = site
        self.kind = kind
        self.count = count          # remaining firings that trigger
        self.after = after          # firings to skip before arming
        self.param = param          # torn/corrupt: fraction; straggle: delay_s


class FaultInjector:
    """Seeded, deterministic injector; use as a context manager.

    ``fired`` records every triggered fault as ``(site, kind, call#)``
    — assert on it for determinism, or to check a fault actually
    landed."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.faults: List[_Fault] = []
        self.calls: Dict[str, int] = {}
        self.fired: List[Tuple[str, str, int]] = []

    # -- scheduling -------------------------------------------------------

    KINDS = ("oserror", "torn", "crash", "sigterm",
             "corrupt", "straggle", "drop", "bitflip",
             "io_error", "hang", "slow")

    def inject(self, site: str, kind: str, count: int = 1, after: int = 0,
               fraction: float = 0.5,
               param: Optional[float] = None) -> "FaultInjector":
        assert kind in self.KINDS, kind
        self.faults.append(_Fault(site, kind, count, after,
                                  fraction if param is None else param))
        return self

    def transient_oserror(self, site: str, count: int,
                          after: int = 0) -> "FaultInjector":
        """Raise ``OSError`` at the next ``count`` firings of ``site``
        (then heal) — the transient-I/O-failure retry scenario."""
        return self.inject(site, "oserror", count=count, after=after)

    def torn_write(self, site: str = "ckpt.write_record", after: int = 0,
                   fraction: float = 0.5) -> "FaultInjector":
        """Write only ``fraction`` of one record's bytes, then die
        (SimulatedCrash) — a kill mid-flush."""
        return self.inject(site, "torn", after=after, fraction=fraction)

    def crash(self, site: str, after: int = 0) -> "FaultInjector":
        """Simulated process death at ``site`` (kill mid-async-save)."""
        return self.inject(site, "crash", after=after)

    def sigterm(self, site: str, after: int = 0) -> "FaultInjector":
        """Deliver a real SIGTERM to this process when ``site`` fires
        (exercises an installed preemption handler)."""
        return self.inject(site, "sigterm", after=after)

    def corrupt(self, site: str, fraction: float = 0.05, after: int = 0,
                count: int = 1) -> "FaultInjector":
        """Corrupt ``fraction`` of this rank's local view of a
        collective result (scale corruption — a lossy link)."""
        return self.inject(site, "corrupt", count=count, after=after,
                           param=fraction)

    def straggle(self, site: str, delay_s: float = 0.25, after: int = 0,
                 count: int = 1) -> "FaultInjector":
        """Delay this rank ``delay_s`` seconds before it joins the
        collective (peers stall waiting — a straggler rank)."""
        return self.inject(site, "straggle", count=count, after=after,
                           param=delay_s)

    def drop(self, site: str, after: int = 0,
             count: int = 1) -> "FaultInjector":
        """Skip the collective on this rank; peers hang in it until a
        watchdog deadline fires."""
        return self.inject(site, "drop", count=count, after=after)

    def io_error(self, site: str, after: int = 0,
                 count: int = 1) -> "FaultInjector":
        """Raise a hard ``OSError(EIO)`` at ``site`` — a failing device
        (vs :meth:`transient_oserror`): repeated firings trip the
        tiered-KV degraded mode / the serving replica-death path."""
        return self.inject(site, "io_error", count=count, after=after)

    def hang(self, site: str, seconds: float = 2.0, after: int = 0,
             count: int = 1) -> "FaultInjector":
        """Wedge ``site`` for ``seconds`` (sleep on the site's thread) —
        long enough to blow any watchdog deadline under test, finite so
        the abandoned thread eventually exits."""
        return self.inject(site, "hang", count=count, after=after,
                           param=seconds)

    def slow(self, site: str, seconds: float = 0.1, after: int = 0,
             count: int = 1) -> "FaultInjector":
        """Delay ``site`` by ``seconds`` — a straggler (below the
        watchdog deadline; the hedging threshold's quarry)."""
        return self.inject(site, "slow", count=count, after=after,
                           param=seconds)

    def bitflip(self, site: str, bits: int = 1, after: int = 0,
                count: int = 1) -> "FaultInjector":
        """Flip ``bits`` random bit(s) of the buffer a swap read site
        just filled (silent data corruption between the disk and the
        optimizer update).  ``count=1`` models a transient flip (a
        re-read returns clean bytes); a large ``count`` corrupts every
        re-read too — the quarantine path's quarry."""
        return self.inject(site, "bitflip", count=count, after=after,
                           param=bits)

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultInjector":
        """Parse the subprocess wire format: ``;``-separated faults,
        each a whitespace/comma-separated list of ``key=value`` tokens —
        ``site=`` and ``kind=`` required; ``after=``, ``count=``,
        ``param=`` optional.  For ``hang``/``slow`` faults ``param`` is
        the wedge/delay duration in SECONDS (defaulted to 2.0 when
        omitted — hang specs without a duration must still outlast any
        reasonable watchdog deadline).  Examples::

            site=comm.all_reduce kind=corrupt after=1 param=0.5
            site=replica.hang kind=hang after=3 param=2.5

        (``resilience/distributed.py install_injector_from_env`` plumbs
        this through ``DSTPU_FAULT_SPEC`` into worker processes.)"""
        inj = cls(seed=seed)
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            kv: Dict[str, str] = {}
            for tok in part.replace(",", " ").split():
                k, _, v = tok.partition("=")
                assert _ == "=", f"bad fault-spec token {tok!r} in {spec!r}"
                kv[k] = v
            assert "site" in kv and "kind" in kv, \
                f"fault spec needs site= and kind=: {part!r}"
            param = float(kv["param"]) if "param" in kv else None
            if param is None and kv["kind"] in ("hang", "slow"):
                param = 2.0       # seconds — the serving-site default
            inj.inject(kv["site"], kv["kind"],
                       count=int(kv.get("count", 1)),
                       after=int(kv.get("after", 0)),
                       param=param)
        return inj

    # -- firing -----------------------------------------------------------

    def fire(self, site: str, **ctx: Any) -> Optional[Tuple[str, float]]:
        n = self.calls[site] = self.calls.get(site, 0) + 1
        for f in self.faults:
            if f.site != site or f.count <= 0:
                continue
            if f.after > 0:
                f.after -= 1
                continue
            f.count -= 1
            self.fired.append((site, f.kind, n))
            if f.kind == "oserror":
                raise OSError(f"[fault-injection] transient I/O error at "
                              f"{site} (call {n})")
            if f.kind == "io_error":
                import errno as _errno
                raise OSError(_errno.EIO, f"[fault-injection] hard I/O "
                              f"error at {site} (call {n})")
            if f.kind == "crash":
                raise SimulatedCrash(f"[fault-injection] crash at {site} "
                                     f"(call {n})")
            if f.kind == "sigterm":
                _signal.raise_signal(_signal.SIGTERM)
                return None
            # directive kinds the site must honor: torn (fraction of
            # bytes kept), corrupt (fraction of payload), straggle
            # (delay seconds), drop (skip the op), bitflip (bits to
            # flip in the just-read buffer)
            return (f.kind, f.param)
        return None

    # -- install ----------------------------------------------------------

    def __enter__(self) -> "FaultInjector":
        global _ACTIVE
        assert _ACTIVE is None, "a FaultInjector is already active"
        _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None


_ACTIVE: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def hook(site: str, **ctx: Any) -> Optional[Tuple[str, float]]:
    """Instrumentation point.  Returns ``None`` (the overwhelmingly
    common disarmed case), raises an injected failure, or returns a
    ``(kind, param)`` directive the site must honor — ``("torn",
    fraction)`` for write sites; ``("corrupt", fraction)``,
    ``("straggle", delay_s)`` or ``("drop", 0)`` for comm sites;
    ``("bitflip", bits)`` for swap read sites (honored via
    :func:`apply_bitflip`); ``("hang", seconds)`` / ``("slow",
    seconds)`` for serving sites (honored by sleeping on the site's
    thread)."""
    if _ACTIVE is None:
        return None
    return _ACTIVE.fire(site, **ctx)


def apply_bitflip(buf, nbits: float) -> None:
    """Honor a ``("bitflip", nbits)`` directive: flip ``nbits`` random
    bit(s) of ``buf`` (a contiguous numpy array) in place, positions
    drawn from the active injector's seeded rng — the corruption is
    reproducible from the injector seed alone."""
    import numpy as np

    rng = _ACTIVE.rng if _ACTIVE is not None else random.Random(0)
    view = buf.reshape(-1).view(np.uint8)
    for _ in range(max(1, int(nbits))):
        i = rng.randrange(view.size)
        view[i] ^= np.uint8(1 << rng.randrange(8))


def torn_write_file(path: str, fraction: float = 0.5) -> int:
    """Truncate ``path`` to ``fraction`` of its bytes in place —
    simulates a torn write surfacing AFTER commit (power loss eating
    un-synced pages, storage-layer corruption).  Returns the new
    size."""
    size = max(1, int(__import__("os").path.getsize(path) * fraction))
    with open(path, "rb+") as f:
        f.truncate(size)
    return size


def flip_bit_in_file(path: str, bit: Optional[int] = None,
                     seed: int = 0) -> int:
    """Flip one bit of ``path`` in place — PERSISTENT on-media silent
    corruption (every re-read returns the same flipped bit, unlike the
    transient ``bitflip`` hook kind).  ``bit`` is the absolute bit
    index; ``None`` picks one from ``seed``.  Returns the flipped bit
    index.  Used by ``scripts/chaos_train.py --sdc`` against live swap
    files."""
    import os

    nbits = os.path.getsize(path) * 8
    assert nbits > 0, f"cannot flip a bit in empty file {path}"
    if bit is None:
        bit = random.Random(seed).randrange(nbits)
    with open(path, "rb+") as f:
        f.seek(bit // 8)
        byte = f.read(1)[0]
        f.seek(bit // 8)
        f.write(bytes([byte ^ (1 << (bit % 8))]))
    return bit
