from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.linear.optimized_linear import (LoRAOptimizedLinear,
                                                   OptimizedLinear,
                                                   QuantizedLinear,
                                                   lora_label_tree,
                                                   mask_lora_frozen)

__all__ = ["LoRAConfig", "QuantizationConfig", "OptimizedLinear",
           "LoRAOptimizedLinear", "QuantizedLinear", "lora_label_tree",
           "mask_lora_frozen"]
