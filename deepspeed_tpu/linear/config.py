"""LoRA / quantization configs (reference ``linear/config.py:13,39``)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List


@dataclass
class LoRAConfig:
    """Reference field set (``linear/config.py:13``); ``offload`` /
    ``offload_ratio`` are accepted for config compatibility — on TPU the
    frozen base either lives in HBM or uses the engine's pinned-host
    offload, there is no per-parameter ratio knob."""

    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False
    target_mods: List[str] = field(default_factory=lambda: [
        "q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj",
        "down_proj"])


@dataclass
class QuantizationConfig:
    """Reference field set (``linear/config.py:39``).  ``q_dtype`` is the
    storage dtype; int8 payload with blockwise scales
    (``ops/quantization.py``) replaces the reference's fp8-in-uint8 CUDA
    buffers."""

    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512
    q_dtype: Any = "int8"
