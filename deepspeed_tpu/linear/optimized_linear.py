"""OptimizedLinear: LoRA + quantized linear layers.

TPU-native re-design of the reference ``linear/optimized_linear.py``
(``OptimizedLinear`` dispatch ``:18``, ``LoRAOptimizedLinear:76``) and
``linear/quantization.py`` (``QuantizedParameter``, ``QuantizedLinear``):

- :class:`LoRAOptimizedLinear` — frozen base weight + trainable low-rank
  adapters: ``y = x @ stop_gradient(W) + (alpha/r) * (x @ A) @ B``.
  ``stop_gradient`` keeps base grads out of the backward graph (XLA prunes
  the dead branch); :func:`mask_lora_frozen` additionally zeroes the
  optimizer state for base leaves so moments are only allocated for
  adapters — together these are the ``requires_grad=False`` semantics.
  ``base_weight_sharding`` annotates the base kernel over the ``data``
  axes (the reference shards it across the DP world the same way); GSPMD
  then keeps one shard per member and gathers inside the matmul.
- :class:`QuantizedLinear` — the base weight is STORED as int8 payload +
  blockwise scales (``ops/quantization.py``; the reference stores fp8 in
  uint8 buffers via ``FP_Quantize``) and dequantized on the fly inside
  the forward — HBM holds 1 byte/param instead of 2.
- :func:`OptimizedLinear` — the reference's dispatch: plain Dense without
  configs, LoRA (optionally quantized base) with them.

A/B init follows the reference (``init_lora``): A kaiming-uniform, B
zeros, so step 0 output equals the base layer exactly.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig

LORA_ADAPTER_NAMES = ("lora_A", "lora_B")
FROZEN_BASE_NAMES = ("base_kernel", "base_kernel_q", "base_kernel_scale",
                     "base_kernel_offset")


def _base_partitioning(cfg: Optional[LoRAConfig]):
    if cfg is None or cfg.base_weight_sharding <= 1:
        return None
    # shard the input dim over the data axes (ZeRO-style memory split;
    # reference flattens across world size the same way)
    return ("data", "data_sub")


class QuantizedLinear(nn.Module):
    """Linear with int8-quantized frozen weight storage (reference
    ``linear/quantization.py QuantizedLinear``)."""

    output_dim: int
    quantization_config: Optional[QuantizationConfig] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from deepspeed_tpu.ops.quantization import quantize

        qcfg = self.quantization_config or QuantizationConfig()
        in_dim = x.shape[-1]

        def init_quantized(rng):
            w = nn.initializers.xavier_uniform()(
                rng, (in_dim, self.output_dim), jnp.float32)
            qt = quantize(w, num_bits=qcfg.q_bits,
                          group_size=min(qcfg.group_size, w.size))
            return {"values": qt.values, "scale": qt.scale,
                    "offset": qt.offset}

        q = self.param("base_kernel_q", lambda rng: init_quantized(rng))
        # dequantize on the fly: int8 payload + scales -> compute dtype;
        # XLA fuses this into the matmul epilogue's operand read
        w = (q["values"].astype(jnp.float32) * q["scale"] + q["offset"])
        w = w.reshape(in_dim, self.output_dim).astype(self.dtype)
        return x @ jax.lax.stop_gradient(w)


class LoRAOptimizedLinear(nn.Module):
    """Frozen base + low-rank adapters (reference
    ``optimized_linear.py:76``).  ``bias=True`` is unsupported, like the
    reference."""

    input_dim: int
    output_dim: int
    lora_config: LoRAConfig
    quantization_config: Optional[QuantizationConfig] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        cfg = self.lora_config
        assert cfg is not None, "LoRAOptimizedLinear requires a LoRA config"
        scaling = cfg.lora_alpha / cfg.lora_r

        if self.quantization_config is not None:
            from deepspeed_tpu.ops.quantization import quantize

            qcfg = self.quantization_config

            def init_q(rng):
                w = nn.initializers.xavier_uniform()(
                    rng, (self.input_dim, self.output_dim), jnp.float32)
                qt = quantize(w, num_bits=qcfg.q_bits,
                              group_size=min(qcfg.group_size, w.size))
                return {"values": qt.values, "scale": qt.scale,
                        "offset": qt.offset}

            q = self.param("base_kernel_q", init_q)
            base_w = (q["values"].astype(jnp.float32) * q["scale"]
                      + q["offset"]).reshape(
                self.input_dim, self.output_dim).astype(self.dtype)
        else:
            init = nn.initializers.xavier_uniform()
            part = _base_partitioning(cfg)
            if part is not None:
                init = nn.with_partitioning(init, (part, None))
            base_w = self.param("base_kernel", init,
                                (self.input_dim, self.output_dim),
                                self.dtype)
        base_w = jax.lax.stop_gradient(base_w)

        # A: kaiming uniform (reference init_lora follows peft); B: zeros
        # so the initial output equals the base layer
        a = self.param("lora_A",
                       nn.initializers.variance_scaling(
                           1.0 / 3.0, "fan_in", "uniform"),
                       (self.input_dim, cfg.lora_r), self.dtype)
        b = self.param("lora_B", nn.initializers.zeros,
                       (cfg.lora_r, self.output_dim), self.dtype)
        return x @ base_w + scaling * ((x @ a) @ b)


def OptimizedLinear(input_dim: int, output_dim: int, bias: bool = False,
                    lora_config: Optional[LoRAConfig] = None,
                    quantization_config: Optional[QuantizationConfig] = None,
                    dtype: Any = jnp.bfloat16) -> nn.Module:
    """Dispatch (reference ``OptimizedLinear.__new__``): plain Dense
    without configs; quantized-only; or LoRA (optionally quantized)."""
    assert not bias, "bias=True is not supported by OptimizedLinear"
    if lora_config is None and quantization_config is None:
        return nn.Dense(output_dim, use_bias=False, dtype=dtype,
                        param_dtype=dtype)
    if lora_config is None:
        return QuantizedLinear(output_dim=output_dim,
                               quantization_config=quantization_config,
                               dtype=dtype)
    return LoRAOptimizedLinear(input_dim=input_dim, output_dim=output_dim,
                               lora_config=lora_config,
                               quantization_config=quantization_config,
                               dtype=dtype)


# ---------------------------------------------------------------------------
# trainability plumbing (torch requires_grad=False -> optax masking)
# ---------------------------------------------------------------------------

def lora_label_tree(params) -> Any:
    """Label each leaf "frozen" (base weights) or "trainable" (adapters
    and everything else) by parameter name, for ``optax.multi_transform``
    or :func:`mask_lora_frozen`."""
    import jax.tree_util as jtu

    flat, treedef = jtu.tree_flatten_with_path(params)
    labels = []
    for kp, _ in flat:
        names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in kp]
        frozen = any(n in FROZEN_BASE_NAMES for n in names)
        labels.append("frozen" if frozen else "trainable")
    return jtu.tree_unflatten(treedef, labels)


def mask_lora_frozen(tx: optax.GradientTransformation
                     ) -> optax.GradientTransformation:
    """Wrap an optimizer so frozen base weights get no updates AND no
    optimizer state (moments only for adapters — the LoRA memory win)."""
    def mask_fn(params):
        import jax.tree_util as jtu

        return jtu.tree_map(lambda l: l == "trainable",
                            lora_label_tree(params))

    return optax.masked(tx, mask_fn)
